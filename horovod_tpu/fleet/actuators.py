"""Fleet actuators: the ONLY cohort-mutation surface outside the
drivers.

Everything here is an **idempotent desired-state write** — target
files the elastic discovery scripts read, drain flags on the KV
plane, transfer markers in the ledger. That property is what makes
the arbiter's crash story simple: a promoted standby that finds a
lease mid-flight re-issues the current state's actuation verbatim
(ledger.resume_action) and nothing double-fires, because writing the
same target file or raising an already-raised drain flag twice is a
no-op.

hvd-lint HVD212 enforces the flip side: worker processes are spawned
and terminated *only* by the elastic drivers reconciling these
desired-state writes (runner/elastic_driver.py, runner/spawn.py) —
code that reaches for SlotProcess/terminate directly bypasses the
lease ledger, the journal, and the blacklist accounting at once.

The stock actuator set drives both planes through the same elastic
machinery the autoscaler uses (serving/autoscale.py write_target):
shrinking the training target file makes the training driver deliver
graceful SIGTERM preemption at the next commit boundary (exit 83 →
membership change, reshard, zero lost steps), growing the serving
target spawns serving workers that join through the normal
router/rendezvous paths.

When the serving plane has live migration wired (serving/migration.py,
docs/serving.md "Live migration"), the drain flags raised here are
migration-backed: the drained worker hands its in-flight KV pages to a
surviving peer instead of decoding them to completion, so the serve →
train chip ebb returns slots in O(transfer) rather than O(longest
stream), with zero re-prefills. Without a peer or on a refused
transfer the drain degrades — loudly — to the original finish-locally
path; either way no accepted request is lost.
"""

from ..chaos import inject as _chaos_inject
from ..serving.autoscale import write_target
from ..serving.worker import SERVING_SCOPE
from ..utils.logging_util import get_logger


class TargetFileActuators:
    """Desired-state writes for a single-host slot budget: the
    training cohort is ``host:0..n-1`` of the training target file,
    the serving cohort ``host:0..m-1`` of the serving one. ``kv_put``
    (a ``(scope, key, value)`` callable) carries drain flags to the
    serving plane; None disables them (callers that drain through
    their own channel)."""

    def __init__(self, train_target, serve_target, *,
                 host="localhost", serve_cohort="serve", kv_put=None):
        self.train_target = train_target
        self.serve_target = serve_target
        self.host = host
        self.serve_cohort = serve_cohort
        self.kv_put = kv_put
        self._log = get_logger()

    # -- victim selection --------------------------------------------------
    def pick_train_victims(self, old_slots, new_slots):
        """Shrinking a ``host:slots`` line drops the highest slot
        indices — pick exactly those so the ledger's transfer markers
        name the workers the driver will actually preempt."""
        return [f"{self.host}:{i}" for i in range(new_slots,
                                                  old_slots)]

    def pick_serve_victims(self, old_slots, new_slots):
        return [f"{self.host}:{i}" for i in range(new_slots,
                                                  old_slots)]

    # -- desired-state writes ----------------------------------------------
    def set_train_slots(self, slots):
        self._log.info("fleet actuate: training target -> %d slot(s)",
                       slots)
        lines = [f"{self.host}:{slots}"] if slots > 0 else []
        write_target(self.train_target, lines)

    def set_serve_slots(self, slots):
        self._log.info("fleet actuate: serving target -> %d slot(s)",
                       slots)
        lines = [f"{self.host}:{slots}"] if slots > 0 else []
        write_target(self.serve_target, lines)

    def drain(self, wid):
        """Raise the per-worker drain flag for one serving victim
        (serving/worker.py polls ``drain.<cohort>.<wid>``). Per-worker
        and slot-index-keyed, so the ebb of one slot never drains the
        survivors of the same cohort."""
        slot = wid.rsplit(":", 1)[-1]
        _chaos_inject("drain", name=self.serve_cohort, wid=wid)
        if self.kv_put is None:
            return
        self._log.info("fleet actuate: draining serving worker %s.%s",
                       self.serve_cohort, slot)
        self.kv_put(SERVING_SCOPE,
                    f"drain.{self.serve_cohort}.{slot}", "1")


class DriverProbes:
    """Settledness probes over an in-process training ElasticDriver
    plus the serving stats pushed to its KV store — the arbiter polls
    these to decide when a lease may advance. Read-only by design:
    probes observe, actuators write, drivers own processes."""

    def __init__(self, driver, serve_cohort="serve"):
        self.driver = driver
        self.serve_cohort = serve_cohort

    def train_size(self):
        return len(self.driver.workers)

    def train_victims_gone(self, victims):
        return not any(wid in self.driver.workers for wid in victims)

    def serve_members(self):
        """wids registered under ``serving/member.<cohort>.*``."""
        prefix = f"member.{self.serve_cohort}."
        return [key[len(prefix):]
                for key in self.driver.server.scope_keys(SERVING_SCOPE)
                if key.startswith(prefix)]

    def serve_size(self):
        return len(self.serve_members())

    def cohort_stats(self):
        """The serving stats map keyed like Router.stats()['cohorts']
        — one entry per worker here, which is exactly the granularity
        drain/ebb decisions need."""
        out = {}
        prefix = "stats."
        server = self.driver.server
        for key in server.scope_keys(SERVING_SCOPE):
            if not key.startswith(prefix):
                continue
            raw = server.get(SERVING_SCOPE, key)
            if not raw:
                continue
            import json
            try:
                out[key[len(prefix):]] = json.loads(
                    raw if isinstance(raw, str) else raw.decode())
            except ValueError:
                continue
        return out

    def serve_drained(self, victims):
        """A victim is drained when its pushed stats report draining
        with nothing queued or running (accepted requests all
        finished)."""
        stats = self.cohort_stats()
        for wid in victims:
            slot = wid.rsplit(":", 1)[-1]
            s = stats.get(f"{self.serve_cohort}.{slot}")
            if s is None:
                continue  # already gone
            if not s.get("draining"):
                return False
            if int(s.get("queue_depth", 0)) + int(s.get("running",
                                                        0)) > 0:
                return False
        return True
