"""``hvd-fleet``: operator console for the chip-budget arbiter.

    hvd-fleet status --kv HOST:PORT --token T         # split + lease
    hvd-fleet status --kv ... --watch --interval 2    # live
    hvd-fleet knobs                                   # fleet knob table

``status`` reads the durable ``fleet`` KV scope (the lease ledger):
the current train/serve slot split, how many slots are out on
train->serve leases, and the in-flight lease with its state-machine
position — everything a standby promotion would recover from, which
makes this the fastest way to see what a stuck transfer is waiting
on. Exit codes: 0 ok, 2 usage/fetch error.
"""

import argparse
import json
import sys
import time

from . import ledger as ledger_mod
from .policy import fleet_knobs


def _hostport(s):
    host, _, port = s.rpartition(":")
    if not host or not port.isdigit():
        raise argparse.ArgumentTypeError(
            f"expected HOST:PORT, got {s!r}")
    return host, int(port)


def _status_once(ledger):
    split = ledger.split()
    if split is None:
        print("fleet: no recorded split (arbiter never ran here)")
    else:
        print(f"split: train={split['train']} serve={split['serve']} "
              f"leased_out={split.get('leased', 0)}")
    lease = ledger.active()
    if lease is None:
        print("lease: none in flight")
        return
    age = max(0.0, time.time() - lease["created"])
    print(f"lease: {lease['id']}  {lease['direction']}  "
          f"state={lease['state']}  slots={lease['slots']}  "
          f"age={age:.1f}s")
    if lease.get("wids"):
        print(f"  victims: {', '.join(lease['wids'])}")
    chain = ledger_mod.CHAINS[lease["direction"]]
    marks = ("[x]" if chain.index(lease["state"]) >= i else "[ ]"
             for i in range(len(chain)))
    print("  " + "  ".join(f"{m} {s}" for m, s in zip(marks, chain)))


def _cmd_status(args):
    addr, port = args.kv
    ledger = ledger_mod.LeaseLedger(
        ledger_mod.HttpBackend(addr, port, token=args.token))
    try:
        if not args.watch:
            _status_once(ledger)
            return 0
        while True:
            _status_once(ledger)
            print("---", flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    except Exception as e:  # noqa: BLE001 — operator tool: name the failure
        print(f"hvd-fleet: cannot read ledger at {addr}:{port}: {e}",
              file=sys.stderr)
        return 2


def _cmd_lease(args):
    addr, port = args.kv
    ledger = ledger_mod.LeaseLedger(
        ledger_mod.HttpBackend(addr, port, token=args.token))
    try:
        lease = ledger.get(args.id) if args.id else ledger.active()
    except Exception as e:  # noqa: BLE001
        print(f"hvd-fleet: cannot read ledger at {addr}:{port}: {e}",
              file=sys.stderr)
        return 2
    if lease is None:
        print("no such lease" if args.id else "no lease in flight",
              file=sys.stderr)
        return 2
    print(json.dumps(lease, indent=2, sort_keys=True))
    return 0


def _cmd_knobs(_args):
    knobs = fleet_knobs()
    width = max(len(k) for k in knobs)
    for key in sorted(knobs):
        print(f"{key:<{width}}  {knobs[key]}")
    return 0


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="hvd-fleet",
        description="Inspect the fleet arbiter's lease ledger")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("status", help="slot split + in-flight lease")
    p.add_argument("--kv", type=_hostport, required=True,
                   metavar="HOST:PORT")
    p.add_argument("--token", default="")
    p.add_argument("--watch", action="store_true")
    p.add_argument("--interval", type=float, default=2.0)
    p.set_defaults(fn=_cmd_status)

    p = sub.add_parser("lease", help="dump a lease record as JSON")
    p.add_argument("id", nargs="?", default=None,
                   help="lease id (default: the in-flight lease)")
    p.add_argument("--kv", type=_hostport, required=True,
                   metavar="HOST:PORT")
    p.add_argument("--token", default="")
    p.set_defaults(fn=_cmd_lease)

    p = sub.add_parser("knobs", help="resolved HVDTPU_FLEET_* knobs")
    p.set_defaults(fn=_cmd_knobs)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
