"""Telemetry families of the fleet arbiter (docs/metrics.md).

Same lazy-factory contract as the serving plane (serving/metrics.py):
resolution happens at call time, and with ``HOROVOD_TPU_METRICS`` off
every call returns the NULL no-op — the arbiter tick pays a dead
method call, nothing else.
"""


def transfers_total(direction, outcome):
    """``hvd_fleet_transfers_total{direction,outcome}`` — lease
    transfers by direction (``train_to_serve``/``serve_to_train``)
    and outcome (``complete``/``rolled_back``)."""
    from ..telemetry import core as telemetry
    return telemetry.counter(
        "hvd_fleet_transfers_total",
        "Fleet lease transfers, by direction and outcome",
        labelnames=("direction", "outcome"),
    ).labels(direction=direction, outcome=outcome)


def lease_age_seconds():
    """``hvd_fleet_lease_age_seconds`` — age of the in-flight lease
    (0 when none): a transfer stuck mid-flight shows as unbounded
    growth here long before anyone reads the ledger."""
    from ..telemetry import core as telemetry
    return telemetry.gauge(
        "hvd_fleet_lease_age_seconds",
        "Age of the in-flight fleet lease (0 = no transfer running)")


def train_slots():
    """``hvd_fleet_train_slots`` — the training side of the split."""
    from ..telemetry import core as telemetry
    return telemetry.gauge(
        "hvd_fleet_train_slots",
        "Chip slots currently assigned to the training cohort")


def serve_slots():
    """``hvd_fleet_serve_slots`` — the serving side of the split."""
    from ..telemetry import core as telemetry
    return telemetry.gauge(
        "hvd_fleet_serve_slots",
        "Chip slots currently assigned to the serving cohort")
