"""JAX framework binding: drop-in distributed training wrappers.

The reference wraps each framework's optimizer so gradients are allreduced
before the weight update (reference: horovod/torch/optimizer.py:36-275
_DistributedOptimizer grad hooks; horovod/tensorflow/__init__.py:627
DistributedOptimizer with backward_passes_per_step). The JAX-native
equivalent wraps an optax ``GradientTransformation``.

Three reduction flavors, matching how JAX programs are actually written on
TPU:

1. **axis** (compiled, primary): the train step runs under shard_map over
   the replica mesh; gradients reduce with lax.pmean/psum/Adasum over the
   axis — pure XLA collectives on ICI. ``make_train_step`` builds the whole
   step: batch sharded over 'hvd', params replicated, loss pmean'd.
2. **auto** (compiled, implicit): under plain jit with replicated params and
   a batch sharded over the mesh, XLA's SPMD partitioner already inserts the
   gradient reduction — the wrapper is a no-op reduce and only contributes
   aggregation/compression features.
3. **eager** (SPMD multi-process): gradients are concrete arrays; reduce
   rides the eager grouped-allreduce path (torch-style loops on the CPU/TCP
   backend).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import basics
from ..functions import (broadcast_object, broadcast_optimizer_state,
                         broadcast_parameters, broadcast_variables,
                         allgather_object)  # noqa: F401  (re-exported)
from ..ops import reduce_ops
from ..ops.adasum import adasum_axis
from ..ops.compression import Compression
from ..process_sets import global_process_set

HVD_AXIS = "hvd"


from ..utils.jax_compat import axis_size as _axis_size  # noqa: E402
from ..utils.jax_compat import pvary as _pvary  # noqa: E402
from ..utils.jax_compat import shard_map as _shard_map  # noqa: E402


def _reduce_in_axis(grads, op, axis_name, prescale=None, postscale=None):
    def red(g):
        if prescale is not None:
            g = g * jnp.asarray(prescale).astype(g.dtype)
        if op == reduce_ops.Average:
            g = lax.pmean(g, axis_name)
        elif op == reduce_ops.Sum:
            g = lax.psum(g, axis_name)
        elif op == reduce_ops.Adasum:
            g = adasum_axis(g, axis_name)
            # All ranks hold the identical tree-reduction, but the ppermute
            # schedule leaves the value typed device-varying; a psum of g/n
            # is a semantic no-op that re-establishes replica invariance.
            n = _axis_size(axis_name)
            g = lax.psum(g / n, axis_name)
        else:
            raise ValueError(
                f"Unsupported gradient reduction {reduce_ops.op_name(op)}")
        if postscale is not None:
            g = g * jnp.asarray(postscale).astype(g.dtype)
        return g
    return jax.tree.map(red, grads)


class DistributedOptimizer:
    """Optax-compatible distributed optimizer wrapper.

    API shape follows optax (``init``/``update``); semantics follow the
    reference's DistributedOptimizer: gradients are reduced across replicas
    before the inner update, with optional local aggregation over
    ``backward_passes_per_step`` micro-batches (reference:
    horovod/tensorflow/gradient_aggregation.py:16) and fp16/bf16 compression
    of the reduced tensors (reference: horovod/torch/compression.py).

    Args:
      optimizer: inner optax GradientTransformation.
      op: Average (default), Sum, or Adasum.
      axis_name: mesh axis to reduce over when the step runs under
        shard_map; None selects eager (SPMD) or implicit (jit) reduction
        based on the runtime mode.
      backward_passes_per_step: local gradient-aggregation factor.
      compression: Compression.none / fp16 / bf16 applied to reduced grads.
      process_set: eager-mode process set.
    """

    def __init__(self, optimizer, op=reduce_ops.Average, axis_name=None,
                 backward_passes_per_step=1, compression=Compression.none,
                 prescale_factor=None, postscale_factor=None,
                 average_aggregated_gradients=True,
                 process_set=global_process_set):
        self.inner = optimizer
        self.op = op
        self.axis_name = axis_name
        self.k = int(backward_passes_per_step)
        if self.k < 1:
            raise ValueError("backward_passes_per_step must be >= 1")
        self.compression = compression
        self.prescale = prescale_factor
        self.postscale = postscale_factor
        self.average_aggregated = average_aggregated_gradients
        self.process_set = process_set
        # Wire codecs (Compression.int8/fp8) run the quantized pipeline
        # INSIDE the reduction (docs/compression.md): in-jit via
        # quantized_allreduce_axis on the axis path, via the entry codec
        # marker on the eager plane. Adasum needs exact per-rank
        # gradients — reject loudly instead of quantizing them.
        # Bucketed comm/compute overlap (HVDTPU_OVERLAP;
        # docs/performance.md): the in-jit axis reduction is emitted as
        # one collective per ~HVDTPU_BUCKET_BYTES bucket instead of one
        # per leaf, giving XLA's scheduler per-bucket dependencies it
        # can overlap with the remaining backward pass. Read once at
        # construction — the train step bakes the plan at trace time.
        from ..utils import envparse as _ep
        from ..ops import bucketing as _bucketing
        self._overlap = _ep.get_bool(_ep.OVERLAP)
        self._bucket_bytes = _ep.get_int(
            _ep.BUCKET_BYTES, _bucketing.DEFAULT_BUCKET_BYTES)
        self._wire_codec = getattr(compression, "wire_codec", None)
        if self._wire_codec is not None:
            from ..compression import codecs as _codecs
            _codecs.get_codec(self._wire_codec)  # loud on fp8-less jax
            if op not in (reduce_ops.Average, reduce_ops.Sum):
                raise ValueError(
                    f"compression={self._wire_codec!r} supports "
                    "Average/Sum gradient reductions only (Adasum's "
                    "scale-invariant combination needs exact per-rank "
                    "gradients; docs/compression.md)")
            from ..utils import envparse as _envparse
            self._wire_block = _envparse.get_int(
                _envparse.COMPRESSION_BLOCK, _codecs.DEFAULT_BLOCK)

    # -- optax interface ---------------------------------------------------
    def init(self, params):
        inner = self.inner.init(params)
        if self.k == 1:
            return (inner, None, jnp.zeros((), jnp.int32))
        acc = jax.tree.map(jnp.zeros_like, params)
        return (inner, acc, jnp.zeros((), jnp.int32))

    def _reduce(self, grads):
        if self._wire_codec is not None:
            return self._reduce_quantized(grads)
        ctxs = None
        comp_grads = grads
        if self.compression is not Compression.none:
            leaves, treedef = jax.tree.flatten(grads)
            pairs = [self.compression.compress(g) for g in leaves]
            comp_grads = jax.tree.unflatten(treedef, [p[0] for p in pairs])
            ctxs = [p[1] for p in pairs]

        if self.axis_name is not None:
            if self._overlap and self.op in (reduce_ops.Average,
                                             reduce_ops.Sum):
                from ..ops.bucketing import bucketed_reduce_axis
                leaves, treedef = jax.tree.flatten(comp_grads)
                out = jax.tree.unflatten(treedef, bucketed_reduce_axis(
                    leaves, self.op, self.axis_name,
                    bucket_bytes=self._bucket_bytes,
                    prescale=self.prescale, postscale=self.postscale))
            else:
                # Adasum (or OVERLAP=0): per-leaf reduction — Adasum's
                # per-tensor combination cannot be bucketed.
                out = _reduce_in_axis(comp_grads, self.op, self.axis_name,
                                      self.prescale, self.postscale)
        else:
            rt = basics.runtime()
            if rt.mode == basics.MODE_SPMD:
                from ..ops.collectives import grouped_allreduce
                leaves, treedef = jax.tree.flatten(comp_grads)
                reduced = grouped_allreduce(
                    leaves, op=self.op,
                    prescale_factor=self.prescale or 1.0,
                    postscale_factor=self.postscale or 1.0,
                    process_set=self.process_set)
                out = jax.tree.unflatten(treedef, reduced)
            else:
                # Single-controller jit path: XLA's partitioner already
                # reduced the gradients of replicated params — identity.
                out = comp_grads

        if ctxs is not None:
            leaves, treedef = jax.tree.flatten(out)
            out = jax.tree.unflatten(
                treedef, [self.compression.decompress(g, c)
                          for g, c in zip(leaves, ctxs)])
        return out

    def _reduce_quantized(self, grads):
        """Wire-codec reduction: both collective legs carry the
        quantized format. Axis path = in-jit EQuARX pipeline per leaf
        (stateless — error feedback needs cross-step state and lives on
        the eager plane); eager SPMD path = the entry codec marker
        through grouped_allreduce; single-controller jit path =
        identity (the partitioner already reduced replicated params and
        there is no wire to compress)."""
        from ..compression.codecs import quantized_allreduce_axis

        if self.axis_name is not None:
            average = self.op == reduce_ops.Average
            if self._overlap:
                # One quantized pipeline per bucket: both collective
                # legs of every bucket ride the wire format, and the
                # per-bucket dependencies overlap with backprop exactly
                # like the plain bucketed path (docs/performance.md).
                from ..ops.bucketing import bucketed_reduce_axis
                leaves, treedef = jax.tree.flatten(grads)
                return jax.tree.unflatten(treedef, bucketed_reduce_axis(
                    leaves, self.op, self.axis_name,
                    bucket_bytes=self._bucket_bytes,
                    prescale=self.prescale, postscale=self.postscale,
                    wire_codec=self._wire_codec,
                    block=self._wire_block))

            def red(g):
                if self.prescale is not None:
                    g = g * jnp.asarray(self.prescale).astype(g.dtype)
                g = quantized_allreduce_axis(
                    g, self.axis_name, codec=self._wire_codec,
                    block=self._wire_block, average=average)
                if self.postscale is not None:
                    g = g * jnp.asarray(self.postscale).astype(g.dtype)
                return g
            return jax.tree.map(red, grads)

        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD:
            from ..ops.collectives import grouped_allreduce
            leaves, treedef = jax.tree.flatten(grads)
            reduced = grouped_allreduce(
                leaves, op=self.op, compression=self.compression,
                prescale_factor=self.prescale or 1.0,
                postscale_factor=self.postscale or 1.0,
                process_set=self.process_set)
            return jax.tree.unflatten(treedef, reduced)
        return grads

    def update(self, grads, state, params=None):
        inner_state, acc, count = state
        if self.k == 1:
            reduced = self._reduce(grads)
            updates, new_inner = self.inner.update(reduced, inner_state,
                                                   params)
            return updates, (new_inner, None, count + 1)
        if self.axis_name is not None or _is_traced(grads):
            return self._update_aggregated_traced(grads, state, params)
        return self._update_aggregated_eager(grads, state, params)

    # -- local gradient aggregation ---------------------------------------
    def _update_aggregated_traced(self, grads, state, params):
        """Compiled-path aggregation: the per-replica gradient is reduced
        every micro-step and the *reduced* gradient is accumulated, so the
        optimizer state stays replica-invariant (required for the
        replicated out_specs of the train step). For Sum/Average this is
        mathematically identical to the reference's accumulate-then-reduce
        (reduction is linear) and XLA overlaps the extra collectives with
        compute; the comm-sparing accumulate-then-reduce variant lives on
        the eager SPMD path below."""
        inner_state, acc, count = state
        g = self._reduce(grads)
        acc = jax.tree.map(jnp.add, acc, g)
        count = count + 1
        do_step = (count % self.k) == 0

        g = acc
        if self.average_aggregated:
            g = jax.tree.map(lambda a: a / self.k, g)
        updates, stepped_inner = self.inner.update(g, inner_state, params)

        # Merge the stepped and held states with a select rather than
        # lax.cond: the optimizer update is a few elementwise ops per
        # parameter (noise next to the backward pass), and cond branches
        # break the shard_map replication checker on pre-vma jax
        # ("branches produced mismatched replication types").
        def pick(a, b):
            return jnp.where(do_step, a, b)

        updates = jax.tree.map(lambda u: pick(u, jnp.zeros_like(u)),
                               updates)
        new_inner = jax.tree.map(pick, stepped_inner, inner_state)
        new_acc = jax.tree.map(lambda a: pick(jnp.zeros_like(a), a), acc)
        return updates, (new_inner, new_acc, count)

    def _update_aggregated_eager(self, grads, state, params):
        inner_state, acc, count = state
        acc = jax.tree.map(jnp.add, acc, grads)
        count = int(count) + 1
        if count % self.k == 0:
            g = acc
            if self.average_aggregated:
                g = jax.tree.map(lambda a: a / self.k, g)
            g = self._reduce(g)
            updates, new_inner = self.inner.update(g, inner_state, params)
            acc = jax.tree.map(jnp.zeros_like, acc)
            return updates, (new_inner, acc,
                             jnp.asarray(count, jnp.int32))
        updates = jax.tree.map(jnp.zeros_like, grads)
        return updates, (inner_state, acc, jnp.asarray(count, jnp.int32))


def _is_traced(tree):
    import jax.core
    return any(isinstance(x, jax.core.Tracer) for x in jax.tree.leaves(tree))


def DistributedAdasumOptimizer(optimizer, axis_name=None, **kwargs):
    """Adasum flavor (reference: horovod/tensorflow/__init__.py:530
    _DistributedAdasumOptimizer)."""
    return DistributedOptimizer(optimizer, op=reduce_ops.Adasum,
                                axis_name=axis_name, **kwargs)


def make_train_step(loss_fn, dist_opt, mesh=None, axis_name=HVD_AXIS,
                    donate=True, has_aux=False):
    """Build the canonical single-controller data-parallel train step.

    Without aux state, the returned jitted function
    ``step(params, opt_state, batch) -> (params, opt_state, loss)`` runs
    ``loss_fn(params, batch)`` under shard_map with the batch sharded along
    ``axis_name`` and params replicated; gradients reduce per ``dist_opt``
    (pmean/psum/Adasum) over ICI and the update is applied identically on
    every replica.

    With ``has_aux=True``, ``loss_fn(params, aux, batch) -> (loss,
    new_aux)`` threads non-trained model state (e.g. flax batch_stats), and
    the step signature becomes ``step(params, aux, opt_state, batch) ->
    (params, aux, opt_state, loss)``. The new aux state is pmean'd across
    replicas — the cross-replica running-stat sync of the reference's
    sync_batch_norm (reference: horovod/torch/sync_batch_norm.py).

    This is the TPU-native analog of the reference's per-framework training
    loop integration (reference: examples/tensorflow2/
    tensorflow2_synthetic_benchmark.py training step).
    """
    import optax
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
            # Multi-process job without an explicit mesh: rt.mesh holds
            # ONE local device, so a shard_map pmean over it would be an
            # identity and every rank would silently train alone. Use the
            # per-process plan instead: jitted local compute, gradients
            # reduced eagerly through the process-level data plane (the
            # reference's execution model).
            return _make_hostplane_train_step(loss_fn, dist_opt,
                                              has_aux=has_aux)
        mesh = rt.mesh
    if dist_opt.axis_name is None:
        # Clone rather than mutate: the caller's optimizer object keeps its
        # eager behavior outside this train step.
        import copy
        dist_opt = copy.copy(dist_opt)
        dist_opt.axis_name = axis_name
    elif dist_opt.axis_name != axis_name:
        raise ValueError(
            f"DistributedOptimizer was built for axis "
            f"{dist_opt.axis_name!r} but the train step uses {axis_name!r}")

    def _grads(params, batch, aux=None):
        # Mark params device-varying before differentiating: otherwise the
        # shard_map varying-axes type system auto-psums the gradient of
        # replicated inputs, which would double-count with the explicit
        # reduction below (and would break Adasum, which needs the
        # un-reduced per-replica gradients).
        params_v = jax.tree.map(lambda p: _pvary(p, axis_name), params)
        if has_aux:
            (loss, new_aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params_v, aux, batch)
            new_aux = jax.tree.map(lambda a: lax.pmean(a, axis_name),
                                   new_aux)
            return loss, grads, new_aux
        loss, grads = jax.value_and_grad(loss_fn)(params_v, batch)
        return loss, grads, None

    def body_plain(params, opt_state, batch):
        loss, grads, _ = _grads(params, batch)
        updates, new_opt_state = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, lax.pmean(loss, axis_name)

    def body_aux(params, aux, opt_state, batch):
        loss, grads, new_aux = _grads(params, batch, aux)
        updates, new_opt_state = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (new_params, new_aux, new_opt_state,
                lax.pmean(loss, axis_name))

    # Wire-codec compression ends in an all_gather whose output IS
    # replicated by construction (every rank receives every requantized
    # shard) but the replication checker cannot prove it — same
    # exception as make_zero_train_step's gathered params.
    check = getattr(dist_opt, "_wire_codec", None) is None
    if has_aux:
        sharded = _shard_map(
            body_aux, mesh=mesh,
            in_specs=(P(), P(), P(), P(axis_name)),
            out_specs=(P(), P(), P(), P()), check_vma=check)
        donate_argnums = (0, 1, 2) if donate else ()
    else:
        sharded = _shard_map(
            body_plain, mesh=mesh,
            in_specs=(P(), P(), P(axis_name)),
            out_specs=(P(), P(), P()), check_vma=check)
        donate_argnums = (0, 1) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


def _make_hostplane_train_step(loss_fn, dist_opt, has_aux=False):
    """Per-process SPMD train step: jitted local compute, eager
    cross-process gradient reduction.

    This is the reference's execution model (framework computes the
    backward pass, horovod allreduces the gradients, the optimizer
    applies — reference: horovod/torch/optimizer.py:175-253) realized on
    the process-level data plane (TCP fallback or the xla-global mesh):
    ``jax.value_and_grad(loss_fn)`` is jit-compiled per process, the
    gradient tree rides DistributedOptimizer's eager grouped-allreduce
    (including its comm-sparing backward_passes_per_step aggregation),
    and the optax update applies the reduced gradients. Loss and aux
    state (batch stats) are averaged across ranks like the shard_map
    path pmeans them."""
    import jax as _jax
    import optax

    if dist_opt.axis_name is not None:
        raise ValueError(
            "DistributedOptimizer was built for in-jit axis "
            f"{dist_opt.axis_name!r}; the multi-process host-plane step "
            "reduces eagerly — pass axis_name=None (or supply an "
            "explicit global mesh to make_train_step)")
    grad_fn = _jax.jit(_jax.value_and_grad(loss_fn, has_aux=has_aux))

    def _mean_tree(tree):
        from ..ops.collectives import grouped_allreduce
        leaves, treedef = _jax.tree.flatten(tree)
        if not leaves:
            return tree
        return _jax.tree.unflatten(
            treedef, grouped_allreduce(leaves, op=reduce_ops.Average,
                                       name="hostplane_mean"))

    if has_aux:
        def step(params, aux, opt_state, batch):
            (loss, new_aux), grads = grad_fn(params, aux, batch)
            updates, new_opt = dist_opt.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_aux = _mean_tree(new_aux)
            return new_params, new_aux, new_opt, _mean_tree(loss)
        return step

    def step(params, opt_state, batch):
        loss, grads = grad_fn(params, batch)
        updates, new_opt = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, _mean_tree(loss)
    return step


def make_zero_train_step(loss_fn, dist_opt, mesh=None,
                         axis_name=HVD_AXIS, donate=True):
    """ZeRO-1 variant of :func:`make_train_step`: optimizer state lives
    SHARDED along ``axis_name`` — each replica holds 1/N of the flat
    parameter vector's moments, gradients arrive via reduce-scatter
    instead of allreduce, and updated parameter shards all_gather back
    to the replicated copy. Memory per chip for Adam-family state drops
    from 2x params to 2x params / N (value-add beyond the reference,
    whose data plane always replicates optimizer state).

    Works with elementwise optax transforms (sgd/adam/adamw/...); the
    optimizer sees a flat 1-D shard, so transforms that need the
    parameter tree structure (per-layer masks, clipping by global
    norm) are out of scope — use make_train_step for those.

    Returns ``(step, init_state)``:
      init_state(params) -> sharded opt_state (run once, jitted)
      step(params, opt_state, batch) -> (params, opt_state, loss)
    """
    import optax
    from jax.flatten_util import ravel_pytree
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
            raise RuntimeError(
                "make_zero_train_step has no per-process host-plane "
                "variant: without an explicit global mesh the default "
                "mesh holds one local device and ranks would not sync. "
                "Use make_train_step (host-plane capable) or pass a "
                "jax.distributed global mesh.")
        mesh = rt.mesh
    if dist_opt.axis_name not in (None, axis_name):
        raise ValueError(
            f"DistributedOptimizer was built for axis "
            f"{dist_opt.axis_name!r} but the train step uses "
            f"{axis_name!r}")
    # The ZeRO step owns the gradient reduction (reduce-scatter) and the
    # inner update; DistributedOptimizer features that change either are
    # rejected rather than silently ignored.
    unsupported = []
    if dist_opt.op != reduce_ops.Average:
        unsupported.append(f"op={dist_opt.op!r}")
    if dist_opt.k != 1:
        unsupported.append(f"backward_passes_per_step={dist_opt.k}")
    if dist_opt.compression is not Compression.none:
        unsupported.append("compression")
    if dist_opt.prescale is not None or dist_opt.postscale is not None:
        unsupported.append("prescale/postscale")
    if unsupported:
        raise ValueError(
            "make_zero_train_step supports plain averaged gradients "
            "only; unsupported DistributedOptimizer settings: "
            + ", ".join(unsupported)
            + " (use make_train_step for these)")
    inner = dist_opt.inner
    n = int(mesh.shape[axis_name])

    # Optimizer-state leaves that carry per-parameter moments are 1-D
    # (they mirror the flat shard); scalars (e.g. adam's count) stay
    # replicated. The tree structure is known from a dummy shard.
    state_shape = jax.eval_shape(
        inner.init, jax.ShapeDtypeStruct((n,), jnp.float32))
    state_spec = jax.tree.map(
        lambda s: P(axis_name) if s.ndim >= 1 else P(), state_shape)

    def init_state(params):
        flat, _ = ravel_pytree(params)
        shard_len = (flat.size + (-flat.size) % n) // n
        dtype = flat.dtype

        # Every leaf we mark P(axis_name) must actually mirror the flat
        # parameter shard: an optax transform carrying a non-per-parameter
        # 1-D leaf (e.g. a schedule table) would otherwise be silently
        # sharded along the replica axis and corrupt its layout.
        local_shape = jax.eval_shape(
            inner.init, jax.ShapeDtypeStruct((shard_len,), dtype))
        for path, leaf in jax.tree_util.tree_flatten_with_path(
                local_shape)[0]:
            if leaf.ndim >= 1 and leaf.shape != (shard_len,):
                raise ValueError(
                    "make_zero_train_step requires elementwise optimizer "
                    "state; leaf "
                    + jax.tree_util.keystr(path)
                    + f" has shape {leaf.shape} != ({shard_len},) (the "
                    "per-device parameter shard). Use make_train_step "
                    "for transforms with non-per-parameter state.")

        def body(p):
            del p
            return inner.init(jnp.zeros((shard_len,), dtype))

        return jax.jit(_shard_map(
            body, mesh=mesh, in_specs=(P(),),
            out_specs=state_spec))(params)

    def body(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            jax.tree.map(lambda p: _pvary(p, axis_name), params), batch)
        flat_g, _ = ravel_pytree(grads)
        flat_p, unravel = ravel_pytree(params)
        pad = (-flat_p.size) % n
        if pad:
            flat_g = jnp.pad(flat_g, (0, pad))
            flat_p = jnp.pad(flat_p, (0, pad))
        # The gradient average lands directly in the owning shard: one
        # reduce-scatter replaces the allreduce.
        g_shard = lax.psum_scatter(flat_g, axis_name, tiled=True) / n
        p_shard = flat_p.reshape(n, -1)[lax.axis_index(axis_name)]
        updates, new_opt_state = inner.update(
            g_shard, opt_state, p_shard)
        new_p_shard = optax.apply_updates(p_shard, updates)
        flat_new = lax.all_gather(new_p_shard, axis_name, tiled=True)
        if pad:
            flat_new = flat_new[:flat_new.size - pad]
        return (unravel(flat_new), new_opt_state,
                lax.pmean(loss, axis_name))

    # check_vma off: all_gather'd params are replicated by construction
    # (every rank contributes its shard and receives all others), but the
    # varying-axes type system cannot prove it and would reject the P()
    # out_spec.
    sharded = _shard_map(
        body, mesh=mesh,
        in_specs=(P(), state_spec, P(axis_name)),
        out_specs=(P(), state_spec, P()),
        check_vma=False)
    donate_argnums = (0, 1) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums), init_state
