"""JAX framework binding: drop-in distributed training wrappers.

The reference wraps each framework's optimizer so gradients are allreduced
before the weight update (reference: horovod/torch/optimizer.py:36-275
_DistributedOptimizer grad hooks; horovod/tensorflow/__init__.py:627
DistributedOptimizer with backward_passes_per_step). The JAX-native
equivalent wraps an optax ``GradientTransformation``.

Three reduction flavors, matching how JAX programs are actually written on
TPU:

1. **axis** (compiled, primary): the train step runs under shard_map over
   the replica mesh; gradients reduce with lax.pmean/psum/Adasum over the
   axis — pure XLA collectives on ICI. ``make_train_step`` builds the whole
   step: batch sharded over 'hvd', params replicated, loss pmean'd.
2. **auto** (compiled, implicit): under plain jit with replicated params and
   a batch sharded over the mesh, XLA's SPMD partitioner already inserts the
   gradient reduction — the wrapper is a no-op reduce and only contributes
   aggregation/compression features.
3. **eager** (SPMD multi-process): gradients are concrete arrays; reduce
   rides the eager grouped-allreduce path (torch-style loops on the CPU/TCP
   backend).
"""

import jax
import jax.numpy as jnp
from jax import lax

from .. import basics
from ..functions import (broadcast_object, broadcast_optimizer_state,
                         broadcast_parameters, broadcast_variables,
                         allgather_object)  # noqa: F401  (re-exported)
from ..ops import reduce_ops
from ..ops.adasum import adasum_axis
from ..ops.compression import Compression
from ..process_sets import global_process_set

HVD_AXIS = "hvd"


from ..utils.jax_compat import axis_size as _axis_size  # noqa: E402
from ..utils.jax_compat import pvary as _pvary  # noqa: E402
from ..utils.jax_compat import shard_map as _shard_map  # noqa: E402


def _reduce_in_axis(grads, op, axis_name, prescale=None, postscale=None):
    def red(g):
        if prescale is not None:
            g = g * jnp.asarray(prescale).astype(g.dtype)
        if op == reduce_ops.Average:
            g = lax.pmean(g, axis_name)
        elif op == reduce_ops.Sum:
            g = lax.psum(g, axis_name)
        elif op == reduce_ops.Adasum:
            g = adasum_axis(g, axis_name)
            # All ranks hold the identical tree-reduction, but the ppermute
            # schedule leaves the value typed device-varying; a psum of g/n
            # is a semantic no-op that re-establishes replica invariance.
            n = _axis_size(axis_name)
            g = lax.psum(g / n, axis_name)
        else:
            raise ValueError(
                f"Unsupported gradient reduction {reduce_ops.op_name(op)}")
        if postscale is not None:
            g = g * jnp.asarray(postscale).astype(g.dtype)
        return g
    return jax.tree.map(red, grads)


class DistributedOptimizer:
    """Optax-compatible distributed optimizer wrapper.

    API shape follows optax (``init``/``update``); semantics follow the
    reference's DistributedOptimizer: gradients are reduced across replicas
    before the inner update, with optional local aggregation over
    ``backward_passes_per_step`` micro-batches (reference:
    horovod/tensorflow/gradient_aggregation.py:16) and fp16/bf16 compression
    of the reduced tensors (reference: horovod/torch/compression.py).

    Args:
      optimizer: inner optax GradientTransformation.
      op: Average (default), Sum, or Adasum.
      axis_name: mesh axis to reduce over when the step runs under
        shard_map; None selects eager (SPMD) or implicit (jit) reduction
        based on the runtime mode.
      backward_passes_per_step: local gradient-aggregation factor.
      compression: Compression.none / fp16 / bf16 applied to reduced grads.
      process_set: eager-mode process set.
      zero: ZeRO-1 sharded weight update (``ops/zero.py``): gradients
        reduce-scatter instead of allreduce, each replica steps only
        its 1/n slice of a sharded optimizer state, and updated shards
        allgather back. None reads ``HVDTPU_ZERO``. Axis (shard_map)
        path only; Average/Sum; rejects Adasum and non-global process
        sets at construction (docs/performance.md "ZeRO-1").
    """

    def __init__(self, optimizer, op=reduce_ops.Average, axis_name=None,
                 backward_passes_per_step=1, compression=Compression.none,
                 prescale_factor=None, postscale_factor=None,
                 average_aggregated_gradients=True,
                 process_set=global_process_set, zero=None):
        self.inner = optimizer
        self.op = op
        self.axis_name = axis_name
        self.k = int(backward_passes_per_step)
        if self.k < 1:
            raise ValueError("backward_passes_per_step must be >= 1")
        self.compression = compression
        self.prescale = prescale_factor
        self.postscale = postscale_factor
        self.average_aggregated = average_aggregated_gradients
        self.process_set = process_set
        # Wire codecs (Compression.int8/fp8) run the quantized pipeline
        # INSIDE the reduction (docs/compression.md): in-jit via
        # quantized_allreduce_axis on the axis path, via the entry codec
        # marker on the eager plane. Adasum needs exact per-rank
        # gradients — reject loudly instead of quantizing them.
        # Bucketed comm/compute overlap (HVDTPU_OVERLAP;
        # docs/performance.md): the in-jit axis reduction is emitted as
        # one collective per ~HVDTPU_BUCKET_BYTES bucket instead of one
        # per leaf, giving XLA's scheduler per-bucket dependencies it
        # can overlap with the remaining backward pass. Read once at
        # construction — the train step bakes the plan at trace time.
        from ..utils import envparse as _ep
        from ..ops import bucketing as _bucketing
        from ..autotune import overlay as _overlay
        self._overlap = _ep.get_bool(_ep.OVERLAP)
        # Overlay first: a warm-started (or converged) autotune value
        # for the construction-time bucket knobs wins over the raw env
        # (horovod_tpu/autotune/overlay.py).
        self._bucket_bytes = _overlay.resolve_int(
            _ep.BUCKET_BYTES, _bucketing.DEFAULT_BUCKET_BYTES)
        self._wire_codec = getattr(compression, "wire_codec", None)
        if self._wire_codec is not None:
            from ..compression import codecs as _codecs
            _codecs.get_codec(self._wire_codec)  # loud on fp8-less jax
            if op not in (reduce_ops.Average, reduce_ops.Sum):
                raise ValueError(
                    f"compression={self._wire_codec!r} supports "
                    "Average/Sum gradient reductions only (Adasum's "
                    "scale-invariant combination needs exact per-rank "
                    "gradients; docs/compression.md)")
            from ..utils import envparse as _envparse
            self._wire_block = _envparse.get_int(
                _envparse.COMPRESSION_BLOCK, _codecs.DEFAULT_BLOCK)
        # ZeRO-1 sharded weight update (HVDTPU_ZERO; ops/zero.py,
        # docs/performance.md). Resolved at construction like the
        # overlap knobs; the incompatible combinations are rejected
        # HERE — loudly, not at the first traced step (hvd-lint HVD208
        # flags the same combinations statically).
        self.zero = _ep.get_bool(_ep.ZERO) if zero is None else bool(zero)
        self._zero_rt = None
        if self.zero:
            if op == reduce_ops.Adasum:
                raise ValueError(
                    "zero=True (HVDTPU_ZERO) is incompatible with "
                    "op=Adasum: Adasum's per-tensor scale-invariant "
                    "combination does not reduce-scatter "
                    "(docs/performance.md \"ZeRO-1\"; hvd-lint HVD208)")
            if process_set is not global_process_set:
                raise ValueError(
                    "zero=True (HVDTPU_ZERO) requires the global "
                    "process set: the shard plan partitions state over "
                    "the whole replica axis, and a sub-cohort would "
                    "compute a different (wrong) plan (hvd-lint HVD208)")
            if self.k != 1:
                raise ValueError(
                    "zero=True (HVDTPU_ZERO) does not compose with "
                    "backward_passes_per_step > 1 (accumulate micro-"
                    "batch gradients before the step instead)")
            self._zero_bucket_bytes = _overlay.resolve_int(
                _ep.ZERO_BUCKET_BYTES, _bucketing.DEFAULT_BUCKET_BYTES)
            self._zero_overlay_gen = _overlay.generation()
            self._zero_overlay_pin = False

    # -- ZeRO-1 mode -------------------------------------------------------
    def _zero_codec(self):
        """Codec name the ZeRO legs carry: the wire marker, or the
        cast compressors translated to their codec spelling (the legs
        ride the narrow dtype directly — reference cast semantics)."""
        if self._wire_codec is not None:
            return self._wire_codec, self._wire_block
        if self.compression is Compression.fp16:
            return "fp16", 0
        if self.compression is Compression.bf16:
            return "bf16", 0
        return None, 0

    def _zero_runtime(self, mesh=None, axis_name=None):
        """Build (once) the ZeroRuntime binding inner optimizer × mesh
        × codec. ``init`` resolves the default runtime mesh; the zero
        train step passes its own so both agree — a mismatch is a
        loud error, not a silently different shard plan."""
        from ..ops import zero as _zero
        if self._zero_rt is None:
            if mesh is None:
                rt = basics.runtime()
                if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
                    raise RuntimeError(
                        "HVDTPU_ZERO has no per-process host-plane "
                        "variant: without an explicit global mesh the "
                        "default mesh holds one local device and ranks "
                        "would not sync. Use a jax.distributed global "
                        "mesh, or drop the knob for the host-plane "
                        "step.")
                mesh = rt.mesh
            codec, block = self._zero_codec()
            self._zero_rt = _zero.ZeroRuntime(
                self.inner, mesh, axis_name or self.axis_name or HVD_AXIS,
                op=self.op, bucket_bytes=self._zero_bucket_bytes,
                codec=codec, block=block, prescale=self.prescale,
                postscale=self.postscale)
        elif mesh is not None and self._zero_rt.mesh != mesh:
            raise ValueError(
                "DistributedOptimizer's ZeRO state was initialized for "
                "a different mesh than the train step's; pass the same "
                "mesh to make_train_step and init (or let both default "
                "to the runtime mesh)")
        return self._zero_rt

    def _zero_overlay_stale(self):
        """True when the autotuner's overlay moved
        ``HVDTPU_ZERO_BUCKET_BYTES`` under this optimizer (a zero-arm
        candidate mid-sweep, or a warm-started config landing after
        construction): the shard plan must re-bucket onto the new
        geometry — the caller runs the same deterministic
        re-plan + reshard the elastic version bump takes. One int
        compare per step until the overlay actually moves."""
        from ..autotune import overlay as _overlay
        from ..utils import envparse as _ep
        if self._zero_overlay_pin:
            return False
        gen = _overlay.generation()
        if gen == self._zero_overlay_gen:
            return False
        self._zero_overlay_gen = gen
        v = _overlay.get_int(_ep.ZERO_BUCKET_BYTES)
        if v is None or int(v) == self._zero_bucket_bytes:
            return False
        self._zero_bucket_bytes = int(v)
        return True

    def _zero_rebuild(self, params, opt_state, mesh=None, axis_name=None):
        """Elastic membership changed under us: derive the new plan for
        the current world size and deterministically reshard the
        optimizer state onto it (ops/zero.reshard_state)."""
        from ..ops import zero as _zero
        old = self._zero_rt
        self._zero_rt = None
        new = self._zero_runtime(mesh=mesh, axis_name=axis_name)
        return new, _zero.reshard_state(opt_state, old, new, params)

    # -- optax interface ---------------------------------------------------
    def init(self, params):
        if self.zero:
            return self._zero_runtime().init_state(params)
        inner = self.inner.init(params)
        if self.k == 1:
            return (inner, None, jnp.zeros((), jnp.int32))
        acc = jax.tree.map(jnp.zeros_like, params)
        return (inner, acc, jnp.zeros((), jnp.int32))

    def _reduce(self, grads):
        from ..ops import sparse as sparse_ops
        if any(sparse_ops.is_sparse(leaf) for leaf in jax.tree.leaves(
                grads, is_leaf=sparse_ops.is_sparse)):
            return self._reduce_with_sparse(grads)
        if self._wire_codec is not None:
            return self._reduce_quantized(grads)
        ctxs = None
        comp_grads = grads
        if self.compression is not Compression.none:
            leaves, treedef = jax.tree.flatten(grads)
            pairs = [self.compression.compress(g) for g in leaves]
            comp_grads = jax.tree.unflatten(treedef, [p[0] for p in pairs])
            ctxs = [p[1] for p in pairs]

        if self.axis_name is not None:
            if self._overlap and self.op in (reduce_ops.Average,
                                             reduce_ops.Sum):
                from ..ops.bucketing import bucketed_reduce_axis
                leaves, treedef = jax.tree.flatten(comp_grads)
                out = jax.tree.unflatten(treedef, bucketed_reduce_axis(
                    leaves, self.op, self.axis_name,
                    bucket_bytes=self._bucket_bytes,
                    prescale=self.prescale, postscale=self.postscale))
            else:
                # Adasum (or OVERLAP=0): per-leaf reduction — Adasum's
                # per-tensor combination cannot be bucketed.
                out = _reduce_in_axis(comp_grads, self.op, self.axis_name,
                                      self.prescale, self.postscale)
        else:
            rt = basics.runtime()
            if rt.mode == basics.MODE_SPMD:
                from ..ops.collectives import grouped_allreduce
                leaves, treedef = jax.tree.flatten(comp_grads)
                reduced = grouped_allreduce(
                    leaves, op=self.op,
                    prescale_factor=self.prescale or 1.0,
                    postscale_factor=self.postscale or 1.0,
                    process_set=self.process_set)
                out = jax.tree.unflatten(treedef, reduced)
            else:
                # Single-controller jit path: XLA's partitioner already
                # reduced the gradients of replicated params — identity.
                out = comp_grads

        if ctxs is not None:
            leaves, treedef = jax.tree.flatten(out)
            out = jax.tree.unflatten(
                treedef, [self.compression.decompress(g, c)
                          for g, c in zip(leaves, ctxs)])
        return out

    def _reduce_with_sparse(self, grads):
        """Gradient trees carrying :class:`ops.sparse.SparseGradient`
        leaves (embedding gradients): sparse leaves ride the sparse
        plane — ``HVDTPU_SPARSE`` picks allgather-of-slices vs
        densify-then-allreduce per tensor (docs/sparse.md) — and come
        back DENSE; dense leaves ride the normal reduction unchanged
        (overlap/compression intact). Cast compression skips sparse
        leaves (the plane's row-wise int8 wire codec covers their
        values via the HVDTPU_COMPRESSION name policy instead)."""
        from ..ops import sparse as sparse_ops
        leaves, treedef = jax.tree.flatten(
            grads, is_leaf=sparse_ops.is_sparse)
        sp_pos = {i for i, leaf in enumerate(leaves)
                  if sparse_ops.is_sparse(leaf)}
        dense_leaves = [leaf for i, leaf in enumerate(leaves)
                        if i not in sp_pos]

        def prescaled(sg):
            if self.prescale is None:
                return sg
            return sparse_ops.SparseGradient(
                sg.indices,
                sg.values * jnp.asarray(self.prescale).astype(
                    sg.values.dtype), sg.dense_shape)

        # Eager SPMD path: submit EVERY sparse leaf async BEFORE the
        # dense reduction (which synchronizes internally) and before
        # synchronizing any sparse handle — a blocking call per leaf
        # would serialize one full coordinator cycle per table, the
        # sparse fusion groups can only fuse entries that land in the
        # same cycle batch, and submitting first lets the gathers ride
        # under the dense collective. (In auto mode the per-leaf
        # _cohort_nnz sync still blocks per submission — a scalar
        # allreduce, cheap next to the gather it schedules.) Stable
        # per-leaf names: the HVDTPU_SPARSE glob rules and the density
        # EMA key on them.
        eager_spmd = (self.axis_name is None
                      and basics.runtime().mode == basics.MODE_SPMD)
        handles = {}
        if eager_spmd:
            for i in sorted(sp_pos):
                handles[i] = sparse_ops.sparse_allreduce_async(
                    prescaled(leaves[i]), op=self.op, name=f"grad.sp{i}",
                    process_set=self.process_set)
        reduced_dense = iter(self._reduce(dense_leaves)
                             if dense_leaves else [])

        def red_sparse(sg, i):
            if i in handles:
                from ..ops import collectives as _collectives
                out = _collectives.synchronize(handles[i])
            elif self.axis_name is not None:
                out = sparse_ops.sparse_allreduce_axis(
                    prescaled(sg), self.axis_name, op=self.op,
                    name=f"grad.sp{i}")
            else:
                # Single-controller jit path: the partitioner already
                # reduced replicated params — densify so optax sees a
                # dense update.
                out = prescaled(sg).densify()
            if self.postscale is not None:
                out = out * jnp.asarray(self.postscale).astype(out.dtype)
            return out

        merged = [red_sparse(leaf, i) if i in sp_pos
                  else next(reduced_dense)
                  for i, leaf in enumerate(leaves)]
        return jax.tree.unflatten(treedef, merged)

    def _reduce_quantized(self, grads):
        """Wire-codec reduction: both collective legs carry the
        quantized format. Axis path = in-jit EQuARX pipeline per leaf
        (stateless — error feedback needs cross-step state and lives on
        the eager plane); eager SPMD path = the entry codec marker
        through grouped_allreduce; single-controller jit path =
        identity (the partitioner already reduced replicated params and
        there is no wire to compress)."""
        from ..compression.codecs import quantized_allreduce_axis

        if self.axis_name is not None:
            average = self.op == reduce_ops.Average
            if self._overlap:
                # One quantized pipeline per bucket: both collective
                # legs of every bucket ride the wire format, and the
                # per-bucket dependencies overlap with backprop exactly
                # like the plain bucketed path (docs/performance.md).
                from ..ops.bucketing import bucketed_reduce_axis
                leaves, treedef = jax.tree.flatten(grads)
                return jax.tree.unflatten(treedef, bucketed_reduce_axis(
                    leaves, self.op, self.axis_name,
                    bucket_bytes=self._bucket_bytes,
                    prescale=self.prescale, postscale=self.postscale,
                    wire_codec=self._wire_codec,
                    block=self._wire_block))

            def red(g):
                if self.prescale is not None:
                    g = g * jnp.asarray(self.prescale).astype(g.dtype)
                g = quantized_allreduce_axis(
                    g, self.axis_name, codec=self._wire_codec,
                    block=self._wire_block, average=average)
                if self.postscale is not None:
                    g = g * jnp.asarray(self.postscale).astype(g.dtype)
                return g
            return jax.tree.map(red, grads)

        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD:
            from ..ops.collectives import grouped_allreduce
            leaves, treedef = jax.tree.flatten(grads)
            reduced = grouped_allreduce(
                leaves, op=self.op, compression=self.compression,
                prescale_factor=self.prescale or 1.0,
                postscale_factor=self.postscale or 1.0,
                process_set=self.process_set)
            return jax.tree.unflatten(treedef, reduced)
        return grads

    def update(self, grads, state, params=None):
        if self.zero:
            from ..ops import sparse as sparse_ops
            if any(sparse_ops.is_sparse(leaf) for leaf in
                   jax.tree.leaves(grads,
                                   is_leaf=sparse_ops.is_sparse)):
                raise ValueError(
                    "zero=True (HVDTPU_ZERO) does not accept "
                    "SparseGradient leaves: the ZeRO plan shards the "
                    "FLAT dense state — densify the gradient, or keep "
                    "the embedding on the sparse plane's row-sharded "
                    "state (ops/sparse.plan_row_shards; "
                    "docs/sparse.md)")
            if self._zero_rt is None:
                raise RuntimeError(
                    "ZeRO mode: call init(params) (or run through "
                    "make_train_step) before update — the sharded "
                    "state and shard plan are built there")
            if params is None:
                raise ValueError(
                    "ZeRO mode needs params in update(): the sharded "
                    "optimizer step reads the local parameter shard")
            return self._zero_rt.update_in_axis(grads, state, params)
        inner_state, acc, count = state
        if self.k == 1:
            reduced = self._reduce(grads)
            updates, new_inner = self.inner.update(reduced, inner_state,
                                                   params)
            return updates, (new_inner, None, count + 1)
        if self.axis_name is not None or _is_traced(grads):
            return self._update_aggregated_traced(grads, state, params)
        return self._update_aggregated_eager(grads, state, params)

    # -- local gradient aggregation ---------------------------------------
    def _update_aggregated_traced(self, grads, state, params):
        """Compiled-path aggregation: the per-replica gradient is reduced
        every micro-step and the *reduced* gradient is accumulated, so the
        optimizer state stays replica-invariant (required for the
        replicated out_specs of the train step). For Sum/Average this is
        mathematically identical to the reference's accumulate-then-reduce
        (reduction is linear) and XLA overlaps the extra collectives with
        compute; the comm-sparing accumulate-then-reduce variant lives on
        the eager SPMD path below."""
        inner_state, acc, count = state
        g = self._reduce(grads)
        acc = jax.tree.map(jnp.add, acc, g)
        count = count + 1
        do_step = (count % self.k) == 0

        g = acc
        if self.average_aggregated:
            g = jax.tree.map(lambda a: a / self.k, g)
        updates, stepped_inner = self.inner.update(g, inner_state, params)

        # Merge the stepped and held states with a select rather than
        # lax.cond: the optimizer update is a few elementwise ops per
        # parameter (noise next to the backward pass), and cond branches
        # break the shard_map replication checker on pre-vma jax
        # ("branches produced mismatched replication types").
        def pick(a, b):
            return jnp.where(do_step, a, b)

        updates = jax.tree.map(lambda u: pick(u, jnp.zeros_like(u)),
                               updates)
        new_inner = jax.tree.map(pick, stepped_inner, inner_state)
        new_acc = jax.tree.map(lambda a: pick(jnp.zeros_like(a), a), acc)
        return updates, (new_inner, new_acc, count)

    def _update_aggregated_eager(self, grads, state, params):
        from ..ops import sparse as sparse_ops
        # Local aggregation materializes sparse gradients by
        # construction (the accumulator mirrors the dense params) —
        # same note as the TF binding's accumulator slots. No wire is
        # paid here; the reduce on the k-th step is what the sparse
        # plane would have optimized, and it sees the dense union.
        grads = jax.tree.map(
            lambda g: g.densify() if sparse_ops.is_sparse(g) else g,
            grads, is_leaf=sparse_ops.is_sparse)
        inner_state, acc, count = state
        acc = jax.tree.map(jnp.add, acc, grads)
        count = int(count) + 1
        if count % self.k == 0:
            g = acc
            if self.average_aggregated:
                g = jax.tree.map(lambda a: a / self.k, g)
            g = self._reduce(g)
            updates, new_inner = self.inner.update(g, inner_state, params)
            acc = jax.tree.map(jnp.zeros_like, acc)
            return updates, (new_inner, acc,
                             jnp.asarray(count, jnp.int32))
        updates = jax.tree.map(jnp.zeros_like, grads)
        return updates, (inner_state, acc, jnp.asarray(count, jnp.int32))


def _is_traced(tree):
    import jax.core
    return any(isinstance(x, jax.core.Tracer) for x in jax.tree.leaves(tree))


def DistributedAdasumOptimizer(optimizer, axis_name=None, **kwargs):
    """Adasum flavor (reference: horovod/tensorflow/__init__.py:530
    _DistributedAdasumOptimizer)."""
    return DistributedOptimizer(optimizer, op=reduce_ops.Adasum,
                                axis_name=axis_name, **kwargs)


def make_train_step(loss_fn, dist_opt, mesh=None, axis_name=HVD_AXIS,
                    donate=True, has_aux=False):
    """Build the canonical single-controller data-parallel train step.

    Without aux state, the returned jitted function
    ``step(params, opt_state, batch) -> (params, opt_state, loss)`` runs
    ``loss_fn(params, batch)`` under shard_map with the batch sharded along
    ``axis_name`` and params replicated; gradients reduce per ``dist_opt``
    (pmean/psum/Adasum) over ICI and the update is applied identically on
    every replica.

    With ``has_aux=True``, ``loss_fn(params, aux, batch) -> (loss,
    new_aux)`` threads non-trained model state (e.g. flax batch_stats), and
    the step signature becomes ``step(params, aux, opt_state, batch) ->
    (params, aux, opt_state, loss)``. The new aux state is pmean'd across
    replicas — the cross-replica running-stat sync of the reference's
    sync_batch_norm (reference: horovod/torch/sync_batch_norm.py).

    This is the TPU-native analog of the reference's per-framework training
    loop integration (reference: examples/tensorflow2/
    tensorflow2_synthetic_benchmark.py training step).
    """
    import optax
    from jax.sharding import PartitionSpec as P

    if mesh is None:
        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
            # Multi-process job without an explicit mesh: rt.mesh holds
            # ONE local device, so a shard_map pmean over it would be an
            # identity and every rank would silently train alone. Use the
            # per-process plan instead: jitted local compute, gradients
            # reduced eagerly through the process-level data plane (the
            # reference's execution model).
            if getattr(dist_opt, "zero", False):
                raise RuntimeError(
                    "HVDTPU_ZERO has no per-process host-plane "
                    "variant: pass a jax.distributed global mesh, or "
                    "drop the knob for the host-plane step")
            return _make_hostplane_train_step(loss_fn, dist_opt,
                                              has_aux=has_aux)
        mesh = rt.mesh
    if getattr(dist_opt, "zero", False):
        # ZeRO-1: the state layout (sharded along the axis) and the
        # reduction (reduce-scatter → sharded step → allgather) both
        # change, so the step is built by the dedicated path. The
        # shard plan needs concrete leaf shapes — built lazily on the
        # first call (or by dist_opt.init, whichever runs first).
        if dist_opt.axis_name not in (None, axis_name):
            raise ValueError(
                f"DistributedOptimizer was built for axis "
                f"{dist_opt.axis_name!r} but the train step uses "
                f"{axis_name!r}")
        return _make_zero_step(loss_fn, dist_opt, mesh, axis_name,
                               donate, has_aux)
    if dist_opt.axis_name is None:
        # Clone rather than mutate: the caller's optimizer object keeps its
        # eager behavior outside this train step.
        import copy
        dist_opt = copy.copy(dist_opt)
        dist_opt.axis_name = axis_name
    elif dist_opt.axis_name != axis_name:
        raise ValueError(
            f"DistributedOptimizer was built for axis "
            f"{dist_opt.axis_name!r} but the train step uses {axis_name!r}")

    def _grads(params, batch, aux=None):
        # Mark params device-varying before differentiating: otherwise the
        # shard_map varying-axes type system auto-psums the gradient of
        # replicated inputs, which would double-count with the explicit
        # reduction below (and would break Adasum, which needs the
        # un-reduced per-replica gradients).
        params_v = jax.tree.map(lambda p: _pvary(p, axis_name), params)
        if has_aux:
            (loss, new_aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params_v, aux, batch)
            new_aux = jax.tree.map(lambda a: lax.pmean(a, axis_name),
                                   new_aux)
            return loss, grads, new_aux
        loss, grads = jax.value_and_grad(loss_fn)(params_v, batch)
        return loss, grads, None

    def body_plain(params, opt_state, batch):
        loss, grads, _ = _grads(params, batch)
        updates, new_opt_state = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt_state, lax.pmean(loss, axis_name)

    def body_aux(params, aux, opt_state, batch):
        loss, grads, new_aux = _grads(params, batch, aux)
        updates, new_opt_state = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return (new_params, new_aux, new_opt_state,
                lax.pmean(loss, axis_name))

    # Wire-codec compression ends in an all_gather whose output IS
    # replicated by construction (every rank receives every requantized
    # shard) but the replication checker cannot prove it — same
    # exception as make_zero_train_step's gathered params.
    check = getattr(dist_opt, "_wire_codec", None) is None
    if has_aux:
        sharded = _shard_map(
            body_aux, mesh=mesh,
            in_specs=(P(), P(), P(), P(axis_name)),
            out_specs=(P(), P(), P(), P()), check_vma=check)
        donate_argnums = (0, 1, 2) if donate else ()
    else:
        sharded = _shard_map(
            body_plain, mesh=mesh,
            in_specs=(P(), P(), P(axis_name)),
            out_specs=(P(), P(), P()), check_vma=check)
        donate_argnums = (0, 1) if donate else ()
    return jax.jit(sharded, donate_argnums=donate_argnums)


def _make_hostplane_train_step(loss_fn, dist_opt, has_aux=False):
    """Per-process SPMD train step: jitted local compute, eager
    cross-process gradient reduction.

    This is the reference's execution model (framework computes the
    backward pass, horovod allreduces the gradients, the optimizer
    applies — reference: horovod/torch/optimizer.py:175-253) realized on
    the process-level data plane (TCP fallback or the xla-global mesh):
    ``jax.value_and_grad(loss_fn)`` is jit-compiled per process, the
    gradient tree rides DistributedOptimizer's eager grouped-allreduce
    (including its comm-sparing backward_passes_per_step aggregation),
    and the optax update applies the reduced gradients. Loss and aux
    state (batch stats) are averaged across ranks like the shard_map
    path pmeans them."""
    import jax as _jax
    import optax

    if dist_opt.axis_name is not None:
        raise ValueError(
            "DistributedOptimizer was built for in-jit axis "
            f"{dist_opt.axis_name!r}; the multi-process host-plane step "
            "reduces eagerly — pass axis_name=None (or supply an "
            "explicit global mesh to make_train_step)")
    grad_fn = _jax.jit(_jax.value_and_grad(loss_fn, has_aux=has_aux))

    def _mean_tree(tree):
        from ..ops.collectives import grouped_allreduce
        leaves, treedef = _jax.tree.flatten(tree)
        if not leaves:
            return tree
        return _jax.tree.unflatten(
            treedef, grouped_allreduce(leaves, op=reduce_ops.Average,
                                       name="hostplane_mean"))

    if has_aux:
        def step(params, aux, opt_state, batch):
            (loss, new_aux), grads = grad_fn(params, aux, batch)
            updates, new_opt = dist_opt.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            new_aux = _mean_tree(new_aux)
            return new_params, new_aux, new_opt, _mean_tree(loss)
        return step

    def step(params, opt_state, batch):
        loss, grads = grad_fn(params, batch)
        updates, new_opt = dist_opt.update(grads, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        return new_params, new_opt, _mean_tree(loss)
    return step


def _make_zero_step(loss_fn, dist_opt, mesh, axis_name, donate, has_aux):
    """ZeRO-1 train step (HVDTPU_ZERO; ops/zero.py): the optimizer
    state rides SHARDED through the step (in/out specs from the shard
    plan), gradients reduce-scatter per fusion bucket, the inner
    optimizer steps the local 1/n shard, and updated shards allgather
    back. Built lazily on the first call — the plan needs concrete
    leaf shapes. The outer wrapper also watches the elastic membership
    version: a bump triggers a deterministic reshard of the state to
    the new world size before the re-traced step runs."""
    from jax.sharding import PartitionSpec as P

    # closure state: the jitted fn + the mesh override (dropped after an
    # elastic rebuild so the runtime re-resolves the CURRENT mesh).
    cache = {"fn": None, "mesh": mesh}
    # Bind the runtime NOW (the plan stays lazy): a later
    # dist_opt.init(params) must shard the state over THIS step's mesh,
    # not re-resolve a default that may differ.
    dist_opt._zero_runtime(mesh=mesh, axis_name=axis_name)

    def build(zrt):
        state_spec = zrt.state_specs()

        def _grads(params, batch, aux=None):
            params_v = jax.tree.map(lambda p: _pvary(p, axis_name),
                                    params)
            if has_aux:
                (loss, new_aux), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params_v, aux, batch)
                new_aux = jax.tree.map(
                    lambda a: lax.pmean(a, axis_name), new_aux)
                return loss, grads, new_aux
            loss, grads = jax.value_and_grad(loss_fn)(params_v, batch)
            return loss, grads, None

        # apply_in_axis (not update + optax.apply_updates): the update
        # is applied to the parameter shard BEFORE the allgather, so
        # the optimizer multiply and parameter add compile to the same
        # fused form as the replicated step — bit-identical fp32
        # (ops/zero.py _run docstring).
        def body_plain(params, opt_state, batch):
            loss, grads, _ = _grads(params, batch)
            new_params, new_state = zrt.apply_in_axis(
                grads, opt_state, params)
            return new_params, new_state, lax.pmean(loss, axis_name)

        def body_aux(params, aux, opt_state, batch):
            loss, grads, new_aux = _grads(params, batch, aux)
            new_params, new_state = zrt.apply_in_axis(
                grads, opt_state, params)
            return (new_params, new_aux, new_state,
                    lax.pmean(loss, axis_name))

        # check_vma off: the allgather'd updates are replicated by
        # construction (every rank contributes its shard and receives
        # all others) but the varying-axes type system cannot prove it.
        if has_aux:
            sharded = _shard_map(
                body_aux, mesh=zrt.mesh,
                in_specs=(P(), P(), state_spec, P(axis_name)),
                out_specs=(P(), P(), state_spec, P()), check_vma=False)
            dn = (0, 1, 2) if donate else ()
        else:
            sharded = _shard_map(
                body_plain, mesh=zrt.mesh,
                in_specs=(P(), state_spec, P(axis_name)),
                out_specs=(P(), state_spec, P()), check_vma=False)
            dn = (0, 1) if donate else ()
        return jax.jit(sharded, donate_argnums=dn)

    def step(*args):
        params, opt_state = args[0], args[-2]
        zrt = dist_opt._zero_runtime(mesh=cache["mesh"],
                                     axis_name=axis_name)
        # Poll the overlay FIRST (it refreshes _zero_bucket_bytes as a
        # side effect): a coinciding elastic bump + overlay retune must
        # rebuild ONCE onto the new geometry, not reshard twice.
        overlay_moved = dist_opt._zero_overlay_stale()
        if zrt.stale_version() or overlay_moved:
            zrt, opt_state = dist_opt._zero_rebuild(
                params, opt_state, axis_name=axis_name)
            args = args[:-2] + (opt_state,) + args[-1:]
            cache["fn"] = None
            cache["mesh"] = None
        zrt.ensure_plan(params)
        if cache["fn"] is None:
            cache["fn"] = build(zrt)
        return cache["fn"](*args)

    return step


def make_zero_train_step(loss_fn, dist_opt, mesh=None,
                         axis_name=HVD_AXIS, donate=True):
    """Legacy explicit entry for the ZeRO-1 step (predates the
    ``HVDTPU_ZERO`` mode; kept for its ``(step, init_state)`` return
    shape). The implementation is the ops/zero.py sharded-update plane
    with a single whole-tree bucket, so the sharded state leaves are
    the flat parameter vector's moments padded to N × shard_len —
    exactly the original contract. New code should set ``zero=True``
    (or ``HVDTPU_ZERO=1``) on ``DistributedOptimizer`` and use
    :func:`make_train_step`, which additionally buckets the legs for
    comm/compute overlap and composes with wire compression.

    Returns ``(step, init_state)``:
      init_state(params) -> sharded opt_state (run once, jitted)
      step(params, opt_state, batch) -> (params, opt_state, loss)
    """
    if mesh is None:
        rt = basics.runtime()
        if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
            raise RuntimeError(
                "make_zero_train_step has no per-process host-plane "
                "variant: without an explicit global mesh the default "
                "mesh holds one local device and ranks would not sync. "
                "Use make_train_step (host-plane capable) or pass a "
                "jax.distributed global mesh.")
        mesh = rt.mesh
    if dist_opt.axis_name not in (None, axis_name):
        raise ValueError(
            f"DistributedOptimizer was built for axis "
            f"{dist_opt.axis_name!r} but the train step uses "
            f"{axis_name!r}")
    # The ZeRO step owns the gradient reduction (reduce-scatter) and the
    # inner update; DistributedOptimizer features that change either are
    # rejected rather than silently ignored (the HVDTPU_ZERO mode is
    # less restrictive: Sum and wire compression compose there).
    unsupported = []
    if dist_opt.op != reduce_ops.Average:
        unsupported.append(f"op={dist_opt.op!r}")
    if dist_opt.k != 1:
        unsupported.append(f"backward_passes_per_step={dist_opt.k}")
    if dist_opt.compression is not Compression.none:
        unsupported.append("compression")
    if dist_opt.prescale is not None or dist_opt.postscale is not None:
        unsupported.append("prescale/postscale")
    if unsupported:
        raise ValueError(
            "make_zero_train_step supports plain averaged gradients "
            "only; unsupported DistributedOptimizer settings: "
            + ", ".join(unsupported)
            + " (use make_train_step for these)")

    import copy
    zopt = copy.copy(dist_opt)
    zopt.zero = True
    zopt._zero_rt = None
    # One bucket per dtype: the legacy contract exposes the whole flat
    # vector as a single sharded state leaf per moment. Pinned against
    # the autotune overlay — a zero-arm retune would silently break
    # the single-leaf state shape this entry promises.
    zopt._zero_bucket_bytes = 1 << 62
    zopt._zero_overlay_pin = True

    step = _make_zero_step(loss_fn, zopt, mesh, axis_name, donate,
                           has_aux=False)

    def init_state(params):
        return zopt._zero_runtime(
            mesh=mesh, axis_name=axis_name).init_state(params)

    return step, init_state
