"""Autotuning parameter manager.

The reference tunes fusion-threshold / cycle-time / cache knobs with
Gaussian-process Bayesian optimization (reference:
horovod/common/parameter_manager.cc, optim/bayesian_optimization.cc),
scoring each candidate by observed bytes/sec and broadcasting winners
(reference: controller.cc:39-53 SynchronizeParameters).

TPU-native rethink: the dominant knobs are the same two — fusion threshold
and cycle time — but the search space is small, so a deterministic
coordinate sweep over a discrete grid replaces the GP (the reference's
categorical mode, parameter_manager.h:59-78). Candidate changes are driven
by the CYCLE COUNTER, which is identical on every rank in SPMD mode (each
negotiation round is collective), so all ranks apply the same candidate at
the same cycle without any extra message. Only the final winner needs
cross-rank agreement (scores are timing-noisy): rank 0's choice broadcasts
over the data plane, the analog of SynchronizeParameters.
"""

import time

import numpy as np

from .utils import envparse
from .utils.logging_util import get_logger

# Discrete candidate grids (reference sweeps similar ranges).
FUSION_CANDIDATES_MIB = [0, 1, 2, 4, 8, 16, 32, 64, 128]
CYCLE_CANDIDATES_MS = [0.1, 0.5, 1.0, 2.5, 5.0, 10.0]
WARMUP_CYCLES = 10
CYCLES_PER_CANDIDATE = 20


def _env_list(name, default, conv):
    raw = envparse.get_str(name, "")
    if not raw:
        return default
    return [conv(x) for x in raw.split(",") if x.strip()]


class ParameterManager:
    """Cycle-driven knob sweep; see module docstring."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.enabled = True
        self._log = get_logger()
        self._log_path = envparse.get_str(envparse.AUTOTUNE_LOG, "")
        fusion = _env_list("AUTOTUNE_FUSION_CANDIDATES_MIB",
                           FUSION_CANDIDATES_MIB, float)
        cycle = _env_list("AUTOTUNE_CYCLE_CANDIDATES_MS",
                          CYCLE_CANDIDATES_MS, float)
        self._warmup = envparse.get_int("AUTOTUNE_WARMUP_CYCLES",
                                        WARMUP_CYCLES)
        self._per_candidate = envparse.get_int(
            "AUTOTUNE_CYCLES_PER_CANDIDATE", CYCLES_PER_CANDIDATE)
        self._grid = [(int(f * 1024 * 1024), c) for f in fusion
                      for c in cycle]
        self._cycle = 0
        self._window = 0            # scored cycles under current candidate
        self._idx = -1              # -1 = still warming up
        self._scores = {}           # candidate index -> [bytes/sec]
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self.best = None            # set at convergence

    # -- called once per coordinator cycle --------------------------------
    def record_cycle(self):
        if not self.enabled:
            return
        coord = self.runtime.coordinator
        now = time.monotonic()
        bytes_now = coord.bytes_processed
        if bytes_now == self._last_bytes:
            # Idle cycle: don't advance the sweep (the reference scores
            # traffic, not wall time). Per-cycle executed-byte totals are
            # the negotiated response sizes — identical on every rank and
            # recorded on the cycle thread (delegated completions too:
            # _drain_delegated runs inside the same run_cycle) — so
            # "active cycle" counting keeps the cross-rank determinism.
            self._last_time = now
            return
        self._cycle += 1
        elapsed = now - self._last_time
        score = (bytes_now - self._last_bytes) / max(elapsed, 1e-9)
        self._last_bytes = bytes_now
        self._last_time = now

        if self._idx == -1:
            # Warming up (warmup=0 => candidate 0 applies on the first
            # active cycle; scoring starts the cycle after it applied).
            if self._cycle >= self._warmup:
                self._set_candidate(0)
            return
        self._scores.setdefault(self._idx, []).append(score)
        self._window += 1
        if self._window >= self._per_candidate:
            nxt = self._idx + 1
            if nxt >= len(self._grid):
                self._converge()
            else:
                self._set_candidate(nxt)

    def _set_candidate(self, idx):
        self._idx = idx
        self._window = 0
        self._apply(self._grid[idx])

    def _converge(self):
        """Rank 0's argmax wins and broadcasts over the data plane (the
        SynchronizeParameters analog); ranks reach here at the same point
        in their cycle streams because convergence is cycle-count driven."""
        local_best = max(
            self._scores,
            key=lambda i: sum(self._scores[i]) / len(self._scores[i]))
        rt = self.runtime
        winner = local_best
        from . import basics
        if rt.mode == basics.MODE_SPMD and rt.topology.size > 1:
            from .process_sets import global_process_set
            out = rt.backend.broadcast(
                [np.asarray([local_best], np.int32)], 0,
                global_process_set)
            winner = int(np.asarray(out[0])[0])
        self.best = self._grid[winner]
        self._apply(self.best)
        # Last: observers poll `enabled`, so best/knobs must be in place
        # before the flag flips (the worker thread races this method).
        self.enabled = False
        self._log.info("autotune converged: fusion=%dB cycle=%.2fms",
                       self.best[0], self.best[1])
        if self._log_path:
            with open(self._log_path, "a") as f:
                for idx, scores in sorted(self._scores.items()):
                    cand = self._grid[idx]
                    marker = "*" if idx == winner else ""
                    f.write(f"{cand[0]},{cand[1]},"
                            f"{sum(scores)/len(scores):.1f}{marker}\n")

    def _apply(self, cand):
        fusion, cycle_ms = cand
        coord = self.runtime.coordinator
        coord.fusion_threshold = max(fusion, 1)
        coord.cycle_time_s = cycle_ms / 1000.0
        backend = self.runtime.backend
        if hasattr(backend, "core"):
            # Push the threshold into the native controller (reference:
            # the parameter manager's winners land in the controller's
            # fusion logic). Deterministic across ranks: candidate changes
            # are cycle-count driven.
            backend.core.set_fusion_threshold(max(fusion, 1))
