"""Autotuning parameter manager.

The reference tunes fusion-threshold / cycle-time / cache knobs with
Gaussian-process Bayesian optimization (reference:
horovod/common/parameter_manager.cc, optim/bayesian_optimization.cc),
scoring each candidate by observed bytes/sec and broadcasting winners
(reference: controller.cc:39-53 SynchronizeParameters).

TPU-native rethink: the knob space is small and discrete, so **successive
halving** replaces the GP — every candidate gets a short scoring window,
the top half survives into a longer round (the final head-to-head runs at
the full configured window), repeat until one remains.
Total cycles ≈ 2x an exhaustive sweep at the FINAL budget while having
screened 2^rounds more candidates, which is the bandit-style tradeoff the
reference buys with its GP.

Knobs: fusion threshold and cycle time (the host-plane pair the reference
tunes) plus the **delegated-plane minimum bucket size** — on TPU the
XLA-executed collectives round flat buffers up to a bucket
(backend/xla_global.py _bucket), and a larger minimum bucket turns a
flood of small allreduces into fewer, fuller launches; this is the knob
that actually matters on the chip.

Determinism: candidate changes are driven by the ACTIVE-cycle counter,
identical on every rank in SPMD mode (each negotiation round is
collective), so all ranks apply the same candidate at the same cycle with
no extra message. Scores are timing-noisy and rank-local, so every
round boundary broadcasts rank 0's survivor set over the data plane (the
SynchronizeParameters analog); convergence broadcasts the final winner.
"""

import math
import time

import numpy as np

from .telemetry import core as telemetry
from .utils import envparse
from .utils.logging_util import get_logger

# Discrete candidate grids (reference sweeps similar ranges).
FUSION_CANDIDATES_MIB = [0, 1, 2, 4, 8, 16, 32, 64, 128]
CYCLE_CANDIDATES_MS = [0.1, 0.5, 1.0, 2.5, 5.0, 10.0]
BUCKET_CANDIDATES = [256, 4096, 65536]
WARMUP_CYCLES = 10
CYCLES_PER_CANDIDATE = 20   # budget of the FINAL round; early rounds
                            # screen at budget >> 2^(rounds remaining)


def _env_list(name, default, conv):
    raw = envparse.get_str(name, "")
    if not raw:
        return default
    return [conv(x) for x in raw.split(",") if x.strip()]


class ParameterManager:
    """Cycle-driven successive-halving sweep; see module docstring."""

    def __init__(self, runtime):
        self.runtime = runtime
        self.enabled = True
        self._log = get_logger()
        self._log_path = envparse.get_str(envparse.AUTOTUNE_LOG, "")
        fusion = _env_list(envparse.AUTOTUNE_FUSION_CANDIDATES_MIB,
                           FUSION_CANDIDATES_MIB, float)
        cycle = _env_list(envparse.AUTOTUNE_CYCLE_CANDIDATES_MS,
                          CYCLE_CANDIDATES_MS, float)
        # The bucket knob only exists on delegated (XLA data plane)
        # backends; tuning it elsewhere would burn windows on a no-op.
        if hasattr(runtime.backend, "set_min_bucket"):
            bucket = _env_list(envparse.AUTOTUNE_BUCKET_CANDIDATES,
                               BUCKET_CANDIDATES, int)
        else:
            bucket = [None]
        self._warmup = envparse.get_int(envparse.AUTOTUNE_WARMUP_CYCLES,
                                        WARMUP_CYCLES)
        self._final_budget = envparse.get_int(
            envparse.AUTOTUNE_CYCLES_PER_CANDIDATE, CYCLES_PER_CANDIDATE)
        self._grid = [(int(f * 1024 * 1024), c, b)
                      for f in fusion for c in cycle for b in bucket]
        self._active = list(range(len(self._grid)))
        self._budget = self._round_budget(len(self._active))
        self._pos = -1               # index into _active; -1 = warming up
        self._cycle = 0
        self._window = 0
        self._round_scores = {}      # candidate -> [bytes/sec] this round
        self._history = []           # (round, cand_idx, mean) for the log
        self._round = 0
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self.best = None             # set at convergence
        # Autotune observability (NULL no-ops when metrics off): the
        # knob gauges track the APPLIED values, decision counters the
        # sweep's progress; gauges seed from the coordinator's current
        # config so a scrape before the first candidate shows reality.
        self._m_fusion = telemetry.gauge(
            "hvd_autotune_fusion_threshold_bytes",
            "Fusion threshold currently applied")
        self._m_cycle = telemetry.gauge(
            "hvd_autotune_cycle_time_ms",
            "Coordinator cycle time currently applied")
        self._m_bucket = telemetry.gauge(
            "hvd_autotune_min_bucket",
            "Delegated-plane min bucket currently applied")
        self._m_switches = telemetry.counter(
            "hvd_autotune_candidate_switches_total",
            "Candidate knob applications")
        self._m_rounds = telemetry.counter(
            "hvd_autotune_rounds_total", "Completed halving rounds")
        self._m_converged = telemetry.gauge(
            "hvd_autotune_converged", "1 once the sweep has converged")
        coord = runtime.coordinator
        if coord is not None:
            self._m_fusion.set(coord.fusion_threshold)
            self._m_cycle.set(coord.cycle_time_s * 1000.0)
        self._m_converged.set(0)

    # -- called once per coordinator cycle --------------------------------
    def record_cycle(self):
        if not self.enabled:
            return
        coord = self.runtime.coordinator
        now = time.monotonic()
        bytes_now = coord.bytes_processed
        if bytes_now == self._last_bytes:
            # Idle cycle: don't advance the sweep (the reference scores
            # traffic, not wall time). Per-cycle executed-byte totals are
            # the negotiated response sizes — identical on every rank and
            # recorded on the cycle thread (delegated completions too:
            # _drain_delegated runs inside the same run_cycle) — so
            # "active cycle" counting keeps the cross-rank determinism.
            self._last_time = now
            return
        self._cycle += 1
        elapsed = now - self._last_time
        score = (bytes_now - self._last_bytes) / max(elapsed, 1e-9)
        self._last_bytes = bytes_now
        self._last_time = now

        if self._pos == -1:
            # Warming up (warmup=0 => candidate 0 applies on the first
            # active cycle; scoring starts the cycle after it applied).
            if self._cycle >= self._warmup:
                self._set_position(0)
            return
        cand = self._active[self._pos]
        self._round_scores.setdefault(cand, []).append(score)
        self._window += 1
        if self._window >= self._budget:
            if self._pos + 1 < len(self._active):
                self._set_position(self._pos + 1)
            else:
                self._halve()

    def _round_budget(self, n_active):
        """Scoring window for a round with n_active candidates: the LAST
        round (2 survivors) runs at exactly AUTOTUNE_CYCLES_PER_CANDIDATE;
        earlier rounds screen at that budget halved once per remaining
        halving (floor 2). keep=n//2 needs ceil(log2 n) halvings."""
        if n_active <= 1:
            return self._final_budget
        rounds_left = max(1, math.ceil(math.log2(n_active)))
        return max(2, self._final_budget >> (rounds_left - 1))

    def _set_position(self, pos):
        self._pos = pos
        self._window = 0
        self._apply(self._grid[self._active[pos]])

    def _agree(self, indices):
        """Rank 0's candidate-index selection broadcasts over the data
        plane (the SynchronizeParameters analog); every rank reaches this
        at the same active cycle, so the collective lines up. The vector
        is fixed-length (grid-sized mask) so no shape negotiation is
        needed."""
        rt = self.runtime
        from . import basics
        if rt.mode != basics.MODE_SPMD or rt.topology.size <= 1:
            return indices
        from .process_sets import global_process_set
        mask = np.zeros(len(self._grid), np.int32)
        mask[np.asarray(indices, np.int32)] = 1
        out = rt.backend.broadcast([mask], 0, global_process_set)
        got = np.flatnonzero(np.asarray(out[0]))
        return [int(i) for i in got]

    def _halve(self):
        means = {i: sum(s) / len(s) for i, s in self._round_scores.items()}
        for i, m in sorted(means.items()):
            self._history.append((self._round, i, m))
        keep = max(1, len(self._active) // 2)
        # Ordered by score desc, ties broken by grid order (deterministic
        # on rank 0; everyone else takes the broadcast).
        survivors = sorted(sorted(means), key=lambda i: -means[i])[:keep]
        survivors = self._agree(sorted(survivors))
        if len(survivors) == 1:
            self._converge(survivors[0])
            return
        self._active = survivors
        self._round += 1
        self._m_rounds.inc()
        self._budget = self._round_budget(len(survivors))
        self._round_scores = {}
        self._set_position(0)

    def _converge(self, winner):
        self.best = self._grid[winner]
        self._apply(self.best)
        self._m_converged.set(1)
        # Last: observers poll `enabled`, so best/knobs must be in place
        # before the flag flips (the worker thread races this method).
        self.enabled = False
        self._log.info(
            "autotune converged after %d halving round(s): fusion=%dB "
            "cycle=%.2fms bucket=%s", self._round + 1, self.best[0],
            self.best[1], self.best[2])
        if self._log_path:
            with open(self._log_path, "a") as f:
                for rnd, idx, mean in self._history:
                    cand = self._grid[idx]
                    marker = "*" if idx == winner else ""
                    f.write(f"r{rnd},{cand[0]},{cand[1]},{cand[2]},"
                            f"{mean:.1f}{marker}\n")

    def _apply(self, cand):
        fusion, cycle_ms, bucket = cand
        coord = self.runtime.coordinator
        coord.fusion_threshold = max(fusion, 1)
        coord.cycle_time_s = cycle_ms / 1000.0
        self._m_switches.inc()
        self._m_fusion.set(coord.fusion_threshold)
        self._m_cycle.set(cycle_ms)
        if bucket is not None:
            self._m_bucket.set(bucket)
        backend = self.runtime.backend
        if hasattr(backend, "core"):
            # Push the threshold into the native controller (reference:
            # the parameter manager's winners land in the controller's
            # fusion logic). Deterministic across ranks: candidate changes
            # are cycle-count driven.
            backend.core.set_fusion_threshold(max(fusion, 1))
        if bucket is not None and hasattr(backend, "set_min_bucket"):
            backend.set_min_bucket(bucket)
