"""Autotuning parameter manager.

The reference tunes fusion-threshold / cycle-time / cache knobs with
Gaussian-process Bayesian optimization (reference:
horovod/common/parameter_manager.cc, optim/bayesian_optimization.cc),
scoring each candidate by observed bytes/sec and broadcasting winners.

On TPU the dominant knobs are the same two — fusion threshold and cycle
time — but the search space is small, so we use a deterministic
coordinate-descent sweep over a discrete grid (the reference's categorical
mode, parameter_manager.h:59-78) scored by coordinator bytes/sec. Results
can be logged to HVDTPU_AUTOTUNE_LOG like the reference's
HOROVOD_AUTOTUNE_LOG (reference: operations.cc:588-592).
"""

import time

from .utils import envparse
from .utils.logging_util import get_logger

# Discrete candidate grids (reference sweeps similar ranges).
FUSION_CANDIDATES = [0, 1, 2, 4, 8, 16, 32, 64, 128]      # MiB
CYCLE_CANDIDATES = [0.1, 0.5, 1.0, 2.5, 5.0, 10.0]        # ms
WARMUP_SAMPLES = 3
SAMPLES_PER_CANDIDATE = 10


class ParameterManager:
    def __init__(self, runtime):
        self.runtime = runtime
        self.enabled = True
        self._log = get_logger()
        self._log_path = envparse.get_str(envparse.AUTOTUNE_LOG, "")
        self._samples = 0
        self._warmup_left = WARMUP_SAMPLES
        self._grid = [(f * 1024 * 1024, c)
                      for f in FUSION_CANDIDATES for c in CYCLE_CANDIDATES]
        self._idx = 0
        self._scores = {}
        self._last_bytes = 0
        self._last_time = time.monotonic()
        self._best = None

    def record_cycle(self):
        """Called by the coordinator once per cycle; measures bytes/sec for
        the active candidate and advances the sweep."""
        if not self.enabled:
            return
        coord = self.runtime.coordinator
        now = time.monotonic()
        elapsed = now - self._last_time
        if elapsed < 0.05:
            return
        score = (coord.bytes_processed - self._last_bytes) / elapsed
        self._last_bytes = coord.bytes_processed
        self._last_time = now
        if self._warmup_left > 0:
            self._warmup_left -= 1
            if self._warmup_left == 0:
                # Start measuring under the first candidate's actual knobs.
                self._apply(self._grid[0])
            return
        self._samples += 1
        cand = self._grid[self._idx]
        self._scores.setdefault(cand, []).append(score)
        if self._samples >= SAMPLES_PER_CANDIDATE:
            self._samples = 0
            self._advance()

    def _advance(self):
        self._idx += 1
        if self._idx >= len(self._grid):
            best = max(self._scores,
                       key=lambda c: sum(self._scores[c]) / len(self._scores[c]))
            self._apply(best)
            self._best = best
            self.enabled = False
            self._log.info("autotune converged: fusion=%dB cycle=%.2fms",
                           best[0], best[1])
            if self._log_path:
                with open(self._log_path, "a") as f:
                    for cand, scores in self._scores.items():
                        f.write(f"{cand[0]},{cand[1]},"
                                f"{sum(scores)/len(scores):.1f}\n")
            return
        self._apply(self._grid[self._idx])

    def _apply(self, cand):
        fusion, cycle_ms = cand
        coord = self.runtime.coordinator
        coord.fusion_threshold = max(fusion, 1)
        coord.cycle_time_s = cycle_ms / 1000.0
