"""Gradient compression (reference: horovod/torch/compression.py,
horovod/tensorflow/compression.py — same 74-line API in both bindings).

On TPU the natural wire format is bfloat16 (MXU-native); fp16 is kept for
parity with the reference.
"""

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) needed to decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 before the collective, back after."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return tensor.astype(jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native compression: bfloat16 keeps fp32 dynamic range and is the
    MXU's preferred operand type (no reference analog; TPU value-add)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class Compression:
    """Optional gradient compression algorithms used during allreduce."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
