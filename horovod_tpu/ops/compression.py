"""Gradient compression: the user-facing ``Compression`` surface
(reference: horovod/torch/compression.py,
horovod/tensorflow/compression.py — same API in both bindings).

Two families behind the one reference-shaped class:

- **Cast compressors** (``fp16``/``bf16``): compress/decompress are
  dtype casts around the collective, exactly the reference semantics.
  On TPU the natural wire format is bfloat16 (MXU-native); fp16 is
  kept for parity.
- **Wire compressors** (``int8``/``fp8``): block-wise quantization that
  must be fused INTO the collective (summing raw int8 payloads would
  be garbage), so ``compress`` is an identity and the ``wire_codec``
  marker routes the allreduce through the dispatch plane's quantized
  reduce-scatter → wide-dtype reduce → requantize → allgather pipeline
  (horovod_tpu/compression/; docs/compression.md). Block size, error
  feedback, and policy-based selection ride the ``HVDTPU_COMPRESSION*``
  knobs.
"""

import jax.numpy as jnp


class Compressor:
    """Interface for compressing and decompressing a given tensor."""

    @staticmethod
    def compress(tensor):
        """Returns (compressed_tensor, context) needed to decompress."""
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class FP16Compressor(Compressor):
    """Cast float tensors to fp16 before the collective, back after."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.float16:
            return tensor.astype(jnp.float16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class BF16Compressor(Compressor):
    """TPU-native compression: bfloat16 keeps fp32 dynamic range and is the
    MXU's preferred operand type (no reference analog; TPU value-add)."""

    @staticmethod
    def compress(tensor):
        dtype = tensor.dtype
        if jnp.issubdtype(dtype, jnp.floating) and dtype != jnp.bfloat16:
            return tensor.astype(jnp.bfloat16), dtype
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class _WireCompressor(Compressor):
    """Base for quantized codecs executed inside the collective: the
    user-layer compress/decompress are identities, and ``wire_codec``
    tells the dispatch plane which quantized pipeline to run."""

    wire_codec = None

    @classmethod
    def compress(cls, tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class Int8Compressor(_WireCompressor):
    """Block-wise int8 quantized allreduce (EQuARX pipeline; per-block
    f32 scales, error-feedback residuals on the eager plane)."""

    wire_codec = "int8"


class FP8Compressor(_WireCompressor):
    """Block-wise-scaled float8_e4m3fn quantized allreduce. Needs a jax
    build with ``jnp.float8_e4m3fn`` — selecting it elsewhere is a loud
    error at dispatch, never a silent fp32 fallback."""

    wire_codec = "fp8"


class Compression:
    """Optional gradient compression algorithms used during allreduce."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    fp8 = FP8Compressor
