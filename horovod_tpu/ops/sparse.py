"""Sparse/embedding gradient plane (ISSUE 11; docs/sparse.md).

Embedding-heavy models (DLRM-style recommenders, NMT) produce gradients
that touch a small fraction of table rows per step. Reducing them as
dense tensors pays full-table allreduce wire; gathering (indices,
values) slices pays per-row wire that *grows* with cohort size. The
governing trade-off (PAPERS.md 1905.04035): per rank,

    dense  (ring allreduce)   ~ 2 * R * W * b_v           bytes
    gather (allgather-v)      ~ (n-1) * nnz * (W*b_v + b_i) bytes

with R table rows, W row width, b_v value bytes, b_i index bytes and
``nnz`` locally-touched (deduplicated) rows. Gather wins iff the row
density d = nnz/R stays under the crossover

    d* = theta * 2*W*b_v / ((n-1) * (W*b_v + b_i))

which shrinks ~1/n — the right answer is a per-tensor, **measured**
density policy, not a global switch. ``HVDTPU_SPARSE`` selects it:

    HVDTPU_SPARSE=auto                       # measured density vs d*
    HVDTPU_SPARSE=gather                     # force allgather-of-slices
    HVDTPU_SPARSE='embed*=gather;dense'      # glob rules, first wins

``auto`` smooths the observed density with a per-name EMA
(``HVDTPU_SPARSE_EMA``) so the path choice is stable across steps;
``HVDTPU_SPARSE_THRESHOLD`` scales the crossover (theta above).

Disabled contract (the telemetry/chaos/compression standard): with
``HVDTPU_SPARSE`` unset :func:`make_plane` returns ``None`` — every
sparse gradient densifies into TODAY's dense allreduce path
(bit-identical, guard-tested in tests/test_sparse.py) and the dense
hot path carries zero sparse state.

Wire compression composes: when the ``HVDTPU_COMPRESSION`` policy
selects a wire codec (int8) for a gather-path tensor, the gathered
VALUES ride the wire as row-quantized int8 (one f32 scale per slice
row) — indices are exact always (hvd-lint HVD209 flags scripts that
try). ZeRO composes through :func:`plan_row_shards` /
:func:`rowsharded_update`: embedding optimizer state shards by row
range so the sparse update stays local to the owning shard.
"""

import fnmatch
import re

import numpy as np

from ..analysis import sanitizer
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger
from . import reduce_ops

DEFAULT_THRESHOLD = 1.0   # theta: scales the crossover density
DEFAULT_EMA = 0.8         # history weight of the per-name density EMA
_MODES = ("auto", "gather", "dense")
# The one wire codec the gather path carries on values (row-quantized;
# docs/sparse.md). fp8 is deliberately out: row scales make int8's
# symmetric range the right fit and fp8 support is build-dependent.
_WIRE_CODECS = ("int8",)


# ==========================================================================
# SparseGradient: IndexedSlices-style (indices, values, dense_shape)
# ==========================================================================

class SparseGradient:
    """Row-sparse gradient: ``values[k]`` is the gradient of row
    ``indices[k]`` of a ``dense_shape`` parameter (TF's IndexedSlices,
    torch's COO with sparse_dim=1, reference:
    horovod/tensorflow/__init__.py:55 sparse handling).

    Registered as a jax pytree (indices/values are children,
    dense_shape is static aux data) so it is jit-traceable and can ride
    gradient trees through ``DistributedOptimizer``."""

    __slots__ = ("indices", "values", "dense_shape")

    def __init__(self, indices, values, dense_shape):
        self.indices = indices
        self.values = values
        self.dense_shape = tuple(int(s) for s in dense_shape)

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.indices, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, dense_shape, children):
        indices, values = children
        return cls(indices, values, dense_shape)

    # -- conversions -------------------------------------------------------
    @property
    def nnz(self):
        return int(self.indices.shape[0])

    def densify(self):
        """Segment-sum scatter-add into the dense parameter shape
        (duplicate indices accumulate — IndexedSlices semantics)."""
        import jax.numpy as jnp
        vals = jnp.asarray(self.values)
        out = jnp.zeros(self.dense_shape, vals.dtype)
        return out.at[jnp.asarray(self.indices)].add(vals)

    def deduplicate(self):
        """Host-side row dedup: unique sorted indices, duplicate rows
        segment-summed. Eager plane only (output nnz is data-dependent,
        so this cannot trace)."""
        idx = np.asarray(self.indices)
        vals = np.asarray(self.values)
        uniq, inv = np.unique(idx, return_inverse=True)
        if uniq.shape[0] == idx.shape[0]:
            order = np.argsort(idx, kind="stable")
            return SparseGradient(idx[order], vals[order],
                                  self.dense_shape)
        summed = np.zeros((uniq.shape[0],) + vals.shape[1:], vals.dtype)
        np.add.at(summed, inv, vals)
        return SparseGradient(uniq, summed, self.dense_shape)

    @classmethod
    def from_dense(cls, dense, index_dtype=np.int32):
        """Rows with any nonzero become slices (test/bench helper)."""
        dense = np.asarray(dense)
        rows = np.flatnonzero(
            np.abs(dense).reshape(dense.shape[0], -1).sum(axis=1))
        return cls(rows.astype(index_dtype), dense[rows], dense.shape)

    def __repr__(self):
        return (f"SparseGradient(nnz={self.indices.shape[0]}, "
                f"dense_shape={self.dense_shape})")


def _register_pytree():
    import jax
    jax.tree_util.register_pytree_node(
        SparseGradient,
        lambda sg: sg.tree_flatten(),
        SparseGradient.tree_unflatten)


_register_pytree()


def is_sparse(x):
    return isinstance(x, SparseGradient)


# ==========================================================================
# Row-wise int8 wire codec (values only — indices are exact always)
# ==========================================================================

def encode_rows(values):
    """Symmetric per-row int8 quantization: one f32 scale per slice
    row (scale = maxabs/127, round-trip error <= maxabs/254 — the
    compression plane's bound at block = row). Row-wise (not the fused
    plane's fixed 256-block) because gathered slices are ragged across
    ranks: per-row scales need no block-boundary metadata on the wire."""
    import jax.numpy as jnp
    v = jnp.asarray(values, jnp.float32).reshape(values.shape[0], -1)
    maxabs = jnp.max(jnp.abs(v), axis=1)
    scales = jnp.where(maxabs > 0, maxabs / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(v / scales[:, None]), -127, 127).astype(jnp.int8)
    return q.reshape(values.shape), scales


def decode_rows(q, scales, dtype):
    import jax.numpy as jnp
    qf = jnp.asarray(q, jnp.float32).reshape(q.shape[0], -1)
    out = qf * jnp.asarray(scales, jnp.float32)[:, None]
    return out.reshape(q.shape).astype(dtype)


# ==========================================================================
# Policy: HVDTPU_SPARSE grammar + crossover math + per-name EMA
# ==========================================================================

def crossover_density(world, row_bytes, index_bytes, threshold):
    """Density below which allgather-of-slices beats densify-then-
    allreduce (module docstring math). ``world <= 1`` returns inf:
    there is no wire either way, and the gather path skips the dense
    materialization."""
    if world <= 1:
        return float("inf")
    return (threshold * 2.0 * row_bytes
            / ((world - 1) * (row_bytes + index_bytes)))


def parse_rules(spec):
    """``spec`` -> [(glob, mode)] — the compression-policy grammar with
    gather/dense/auto as the codec vocabulary. Malformed specs raise at
    plane construction (a typo'd knob must never silently disable the
    feature it configures)."""
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            glob, _, mode = part.partition("=")
            glob, mode = glob.strip(), mode.strip()
            if not glob or not mode:
                raise ValueError(
                    f"malformed HVDTPU_SPARSE rule {part!r}: expected "
                    "'<name-glob>=<gather|dense|auto>'")
        else:
            glob, mode = "*", part
        if mode not in _MODES:
            raise ValueError(
                f"unknown HVDTPU_SPARSE mode {mode!r} in rule {part!r} "
                f"(expected one of {', '.join(_MODES)})")
        rules.append((glob, mode))
    return rules


class SparsePolicy:
    """Per-tensor path selection: explicit glob rules override; ``auto``
    compares the EMA-smoothed measured density against the world-scaled
    crossover."""

    def __init__(self, rules, threshold=DEFAULT_THRESHOLD,
                 ema=DEFAULT_EMA):
        self.rules = list(rules)
        self.threshold = float(threshold)
        # A typo'd knob must never silently disable the feature it
        # configures (the parse_rules contract): a non-positive / NaN /
        # inf theta would make auto resolve one path forever, loudly
        # looking like a policy decision.
        if not (self.threshold > 0.0 and np.isfinite(self.threshold)):
            raise ValueError(
                "HVDTPU_SPARSE_THRESHOLD must be a positive finite "
                f"number, got {threshold}")
        if not 0.0 <= float(ema) < 1.0:
            raise ValueError(
                f"HVDTPU_SPARSE_EMA must be in [0, 1), got {ema}")
        self.ema = float(ema)

    @classmethod
    def from_env(cls):
        spec = envparse.get_str(envparse.SPARSE, "")
        return cls(parse_rules(spec),
                   threshold=envparse.get_float(
                       envparse.SPARSE_THRESHOLD, DEFAULT_THRESHOLD),
                   ema=envparse.get_float(envparse.SPARSE_EMA,
                                          DEFAULT_EMA))

    def mode_for_name(self, name):
        for glob, mode in self.rules:
            if fnmatch.fnmatchcase(name or "", glob):
                return mode
        return "dense"


_AUTO_OCCURRENCE = re.compile(r"#\d+$")


def _ema_key(name):
    """Density-state key for one tensor name. Per-call auto names carry
    a '#count' occurrence suffix (one WIRE name per call — HVD203), but
    density is a property of the call site: keying the EMA and the
    `hvd_sparse_density` gauge on the raw name would grow both by one
    entry per training step, unbounded, and `prev` would always be None
    so the EMA never smooths. User-chosen names pass through."""
    if name and ".auto." in name:
        return _AUTO_OCCURRENCE.sub("", name)
    return name


class SparsePlane:
    """Policy + per-name density EMA + telemetry, attached to one
    coordinator (rebuilt on every ``init()``, so EMA state never
    crosses elastic cohorts — the residual-store precedent)."""

    def __init__(self, pol):
        self.policy = pol
        # Submitter threads race on the EMA dict; guarded like every
        # shared map (hvd-lint HVD301), instrumented under sanitize.
        self._lock = sanitizer.make_lock("sparse.plane")
        self._ema = {}
        # Engagement evidence (chaos matrix row): per-path decision
        # counts, readable without the metrics plane.
        self.path_counts = {"gather": 0, "dense": 0}
        self._log = get_logger()
        self._metrics_on = telemetry.enabled()
        self._m_density = telemetry.gauge(
            "hvd_sparse_density",
            "EMA-smoothed nnz-rows/total-rows of a sparse gradient",
            labelnames=("name",))
        self._m_path = telemetry.counter(
            "hvd_sparse_path_total",
            "Sparse-allreduce path decisions", labelnames=("path",))
        self._m_saved = telemetry.counter(
            "hvd_sparse_bytes_saved_total",
            "Wire bytes kept off the fabric by gather-path sparse "
            "collectives vs the densified allreduce")
        # Wire compression on gathered values (docs/sparse.md): the
        # HVDTPU_COMPRESSION name policy decides, the sparse plane only
        # honors wire codecs this plane implements (int8, row-wise).
        self._wire_policy = None
        if envparse.get_str(envparse.COMPRESSION, ""):
            from ..compression.policy import CompressionPolicy
            self._wire_policy = CompressionPolicy.from_env()

    # -- path selection (framework threads) --------------------------------
    def select(self, name, nnz_rows, total_rows, row_bytes, index_bytes,
               world, smooth=True):
        """Resolve gather|dense for one submission and record the
        decision. ``nnz_rows`` is post-dedup; explicit rules skip the
        EMA entirely (their choice is not density-driven).
        ``smooth=False`` decides from the raw observed density with NO
        EMA state read or written — the in-jit axis path, whose
        trace-time decision must not blend unrelated tensors through a
        shared state key or go stale inside a cached trace."""
        mode = self.policy.mode_for_name(name)
        if mode == "auto":
            observed = nnz_rows / max(1, total_rows)
            if smooth:
                key = _ema_key(name)
                with self._lock:
                    prev = self._ema.get(key)
                    smoothed = (observed if prev is None else
                                self.policy.ema * prev
                                + (1.0 - self.policy.ema) * observed)
                    self._ema[key] = smoothed
                if self._metrics_on and key:
                    self._m_density.labels(name=key).set(smoothed)
            else:
                smoothed = observed
            path = ("gather" if smoothed < crossover_density(
                world, row_bytes, index_bytes, self.policy.threshold)
                else "dense")
        else:
            path = mode
        with self._lock:
            self.path_counts[path] += 1
        self._m_path.labels(path=path).inc()
        return path

    def density(self, name):
        """Current EMA for a tensor name (None before first auto
        observation) — test/diagnostic surface. Auto-name occurrence
        suffixes resolve to their call-site key."""
        with self._lock:
            return self._ema.get(_ema_key(name))

    def wire_codec_for(self, name, values_dtype):
        """int8 when the HVDTPU_COMPRESSION policy selects a wire codec
        for this name's VALUES; indices never compress (HVD209)."""
        if self._wire_policy is None:
            return None
        import jax.numpy as jnp
        if not jnp.issubdtype(np.dtype(values_dtype), jnp.floating):
            return None
        codec_name = self._wire_policy.codec_for_name(name)
        if codec_name in _WIRE_CODECS:
            return codec_name
        return None

    # -- accounting (cycle thread / backend sweep) -------------------------
    def record_gather(self, dense_wire_bytes, gather_wire_bytes):
        """Bytes-saved accounting for one executed gather-path
        collective (model bytes — docs/sparse.md methodology)."""
        if self._metrics_on:
            self._m_saved.inc(max(0, int(dense_wire_bytes)
                                  - int(gather_wire_bytes)))


def make_plane():
    """SparsePlane when ``HVDTPU_SPARSE`` is set; None otherwise — the
    disabled-mode contract (zero sparse state on the dense hot path)."""
    spec = envparse.get_str(envparse.SPARSE, "")
    if not spec:
        return None
    return SparsePlane(SparsePolicy.from_env())


def _plane():
    """The live coordinator's sparse plane (None when disabled or
    pre-init)."""
    from .. import basics
    if not basics.is_initialized():
        return None
    return basics.runtime().coordinator._sparse


def enabled():
    return _plane() is not None


# ==========================================================================
# sparse_allreduce: the user-facing collective
# ==========================================================================

class SparseMeta:
    """Per-entry sparse metadata carried on the TensorEntry: what the
    dispatch plane and the guardian digest need beyond the raw arrays.
    ``nranks`` is the per-rank list length in single-controller mode
    (arrays = idx_0..idx_{n-1}, val_0..val_{n-1}); None on the SPMD
    plane (arrays = [idx, val], one rank's slices)."""

    __slots__ = ("dense_shape", "index_dtype", "values_dtype", "nranks",
                 "codec")

    def __init__(self, dense_shape, index_dtype, values_dtype,
                 nranks=None, codec=None):
        self.dense_shape = tuple(int(s) for s in dense_shape)
        self.index_dtype = str(index_dtype)
        self.values_dtype = str(values_dtype)
        self.nranks = nranks
        self.codec = codec


def _validate_op(op, name):
    if op not in (reduce_ops.Sum, reduce_ops.Average):
        raise ValueError(
            f"sparse_allreduce {name!r} supports Sum/Average only, got "
            f"{reduce_ops.op_name(op)}: Adasum needs exact per-tensor "
            "dot products of dense gradients, and Min/Max/Product have "
            "no scatter-add formulation (docs/sparse.md)")


def _check_shapes(slices, name):
    shape = slices[0].dense_shape
    for sg in slices[1:]:
        if sg.dense_shape != shape:
            raise ValueError(
                f"sparse_allreduce {name!r}: per-rank dense_shapes "
                f"disagree ({sg.dense_shape} vs {shape})")
    return shape


def _cohort_nnz(name, nnz, process_set):
    """Cross-rank nnz agreement for the SPMD ``auto`` decision.

    The density feeding the policy must be identical on every rank:
    per-rank nnz legally differs, and a tensor straddling the crossover
    would otherwise split the cohort — some ranks submitting the gather
    path's ``name.idx``/``name.val`` allgathers while others submit a
    plain dense allreduce under ``name``. The native negotiation never
    pairs those, so the job hangs until the stall watchdog aborts, and
    the rank-local EMA makes the disagreement persistent, not
    transient. A scalar Max-allreduce of the local post-dedup nnz
    (same name/shape/dtype on every rank — guardian-silent) gives every
    rank the cohort max, which is also what single-controller mode
    already feeds the policy (max over the virtual ranks' slices)."""
    from . import collectives as _c
    out = _c.allreduce(np.array([nnz], np.int64), name=f"{name}.nnz",
                       op=reduce_ops.Max, process_set=process_set)
    return int(np.asarray(out).reshape(-1)[0])


def sparse_allreduce_async(sparse, average=None, name=None, op=None,
                           process_set=None):
    """Async sparse allreduce of an IndexedSlices-style gradient;
    resolves to the DENSE reduced array (every rank's scatter-add of
    every rank's slices, averaged for ``op=Average``).

    Input convention follows the collectives module: on the SPMD plane
    pass one :class:`SparseGradient` (this rank's slices); in
    single-controller mode pass a LIST of per-rank SparseGradients
    (per-rank nnz legally differs, so slices cannot stack).

    The path — allgather-of-slices vs densify-then-allreduce — comes
    from the ``HVDTPU_SPARSE`` policy (module docstring). With the knob
    unset, or when the policy resolves ``dense``, the call densifies
    and rides TODAY's allreduce path bit-identically (pinned in
    tests/test_sparse.py)."""
    from .. import basics
    from ..coordinator import TensorEntry
    from ..process_sets import global_process_set
    from . import collectives as _c

    if process_set is None:
        process_set = global_process_set
    op = reduce_ops.handle_average_backwards_compatibility(op, average)
    name = name or _c._auto_name("sparse_allreduce")
    _validate_op(op, name)
    rt = basics.runtime()
    single = rt.mode == basics.MODE_SINGLE
    nset = len(process_set.ranks)
    if single:
        if is_sparse(sparse):
            if nset != 1:
                raise ValueError(
                    f"sparse_allreduce {name!r}: single-controller mode "
                    f"needs one SparseGradient per virtual rank (a list "
                    f"of {nset}); per-rank nnz differs so slices cannot "
                    "stack like dense tensors")
            slices = [sparse]
        else:
            slices = list(sparse)
            if len(slices) != nset:
                raise ValueError(
                    f"sparse_allreduce {name!r}: expected one "
                    f"SparseGradient per rank ({nset}), got "
                    f"{len(slices)}")
    else:
        if not is_sparse(sparse):
            raise ValueError(
                f"sparse_allreduce {name!r}: SPMD mode takes this "
                "rank's SparseGradient (lists are single-controller "
                "only)")
        slices = [sparse]
    dense_shape = _check_shapes(slices, name)

    plane = rt.coordinator._sparse
    if plane is None:
        path = "dense"
    else:
        # Local row-deduplication BEFORE the density measurement: the
        # measured density (and the gather wire) is unique-rows, and
        # duplicate indices must accumulate exactly once per
        # contributing row. Only when the resolved mode can gather —
        # an explicit dense rule (and the disabled path above) must
        # stay the pre-plane path, host-side dedup cost included:
        # densify's scatter-add accumulates duplicates anyway.
        if plane.policy.mode_for_name(name) != "dense":
            slices = [sg.deduplicate() for sg in slices]
        vals0 = np.asarray(slices[0].values)
        row_bytes = row_elems(dense_shape) * vals0.dtype.itemsize
        index_bytes = np.asarray(slices[0].indices).dtype.itemsize
        nnz = max(sg.nnz for sg in slices)
        if (not single and nset > 1
                and plane.policy.mode_for_name(name) == "auto"):
            nnz = _cohort_nnz(name, nnz, process_set)
        # world = the cohort the wire spans: virtual ranks in
        # single-controller mode, processes on the SPMD plane.
        path = plane.select(name, nnz, dense_shape[0], row_bytes,
                            index_bytes, nset)

    if path == "dense":
        # Densify-then-allreduce: EXACTLY the pre-sparse-plane path —
        # the entry is a plain dense allreduce, so fusion, overlap,
        # compression and the guardian all see what they saw before
        # this plane existed (bit-identity pinned by test).
        import jax.numpy as jnp
        if single:
            dense = jnp.stack([sg.densify() for sg in slices])
        else:
            dense = slices[0].densify()
        return _c.allreduce_async(dense, name=name, op=op,
                                  process_set=process_set)

    codec = plane.wire_codec_for(name, slices[0].values.dtype)
    meta = SparseMeta(dense_shape,
                      np.asarray(slices[0].indices).dtype,
                      np.asarray(slices[0].values).dtype,
                      nranks=(len(slices) if single else None),
                      codec=codec)
    arrays = ([np.asarray(sg.indices) for sg in slices]
              + [np.asarray(sg.values) for sg in slices])
    entry = TensorEntry(name, "sparse_allreduce", arrays, process_set,
                        op=op)
    entry.sparse = meta
    return _c._submit(entry)


def sparse_allreduce(sparse, average=None, name=None, op=None,
                     process_set=None):
    """Blocking :func:`sparse_allreduce_async`."""
    from . import collectives as _c
    return _c.synchronize(sparse_allreduce_async(
        sparse, average=average, name=name, op=op,
        process_set=process_set))


# ==========================================================================
# Execution helpers shared by the coordinator and the TCP backend
# ==========================================================================

def scatter_add_dense(indices, values, dense_shape, world, op,
                      dtype=None):
    """Gathered (indices, values) -> the dense reduction: scatter-add
    (order-invariant, duplicates across ranks accumulate) then /world
    for Average. The one reduction both transports share."""
    import jax.numpy as jnp
    vals = jnp.asarray(values)
    if dtype is not None:
        vals = vals.astype(dtype)
    out = jnp.zeros(dense_shape, vals.dtype)
    out = out.at[jnp.asarray(indices)].add(vals)
    if op == reduce_ops.Average:
        out = (out / world).astype(vals.dtype)
    return out


def row_elems(dense_shape):
    """Elements per row (product of the trailing dims) — the one unit
    wire accounting, segment offsets, and the crossover math all agree
    on; every caller must stay on this helper or the planes diverge."""
    return int(np.prod(dense_shape[1:])) if len(dense_shape) > 1 else 1


def gather_wire_bytes(nnz_total, row_elems, values_itemsize,
                      index_itemsize, world, codec=None):
    """Model wire bytes PER RANK of the gather transport: every rank
    receives the other ranks' slices ((n-1)/n of the gathered total).
    With the int8 row codec values carry 1 byte/elem + one f32 scale
    per row."""
    if codec == "int8":
        per_row = row_elems + 4 + index_itemsize
    else:
        per_row = row_elems * values_itemsize + index_itemsize
    frac = (world - 1) / world if world > 1 else 0.0
    return int(nnz_total * per_row * frac)


def dense_wire_bytes(dense_shape, values_itemsize):
    """Model wire bytes PER RANK of the densified ring allreduce
    (~2x the payload: reduce-scatter + allgather legs)."""
    return int(2 * int(np.prod(dense_shape)) * values_itemsize)


# ==========================================================================
# In-jit axis path (shard_map train steps)
# ==========================================================================

def sparse_allreduce_axis(sg, axis_name, op=reduce_ops.Average,
                          name=None):
    """In-jit sparse allreduce over a mesh axis: all_gather the
    (indices, values) slices (per-replica nnz is equal by construction
    under shard_map — shapes are static), scatter-add into the dense
    shape. The path decision is static too (trace-time density vs the
    crossover — no EMA in-jit; the host plane owns the smoothed
    policy): with no plane, or above the crossover, this densifies and
    psums exactly like a dense gradient."""
    import jax.numpy as jnp
    from jax import lax
    from ..utils.jax_compat import axis_size as _axis_size

    _validate_op(op, name or "<axis>")
    n = _axis_size(axis_name)
    plane = _plane()
    path = "dense"
    if plane is not None:
        vals = sg.values
        path = plane.select(name or "<axis>", int(sg.indices.shape[0]),
                            sg.dense_shape[0],
                            row_elems(sg.dense_shape) * vals.dtype.itemsize,
                            np.dtype(sg.indices.dtype).itemsize, int(n),
                            smooth=False)
    if path == "dense":
        dense = sg.densify()
        red = lax.pmean(dense, axis_name) if op == reduce_ops.Average \
            else lax.psum(dense, axis_name)
        return red
    idx_g = lax.all_gather(sg.indices, axis_name, tiled=True)
    val_g = lax.all_gather(sg.values, axis_name, tiled=True)
    dense = jnp.zeros(sg.dense_shape, val_g.dtype)
    dense = dense.at[idx_g].add(val_g)
    if op == reduce_ops.Average:
        dense = (dense / n).astype(val_g.dtype)
    return dense


# ==========================================================================
# ZeRO composition: embedding optimizer state sharded by row range
# ==========================================================================

def plan_row_shards(nrows, world):
    """Contiguous near-even row ranges, one per rank: [(lo, hi), ...]
    (earlier ranks take the remainder, the reducescatter convention).
    Deterministic in (nrows, world) — the cross-rank identity the ZeRO
    plane's plan signature pins."""
    base, rem = divmod(int(nrows), int(world))
    bounds, start = [], 0
    for r in range(world):
        end = start + base + (1 if r < rem else 0)
        bounds.append((start, end))
        start = end
    return bounds


def rowsharded_update(opt, gathered, param_shard, state_shard, lo, hi):
    """Apply the gathered sparse gradient to THIS rank's row range.

    ``gathered`` is the post-allgather deduplicated global slice set
    (what the gather path produces before scatter-add); rows outside
    [lo, hi) belong to other shards and are dropped here — the sparse
    update stays local to the owning shard, and the optimizer state for
    the embedding table lives row-sharded (1/n per rank) instead of
    replicated. Only the TOUCHED local rows step (sparse-apply
    semantics: untouched rows keep their moments, like torch's
    SparseAdam); ``opt`` must be an elementwise optax transform whose
    state leaves mirror the parameter rows (the ops/zero.py
    elementwise-state contract).

    Returns (new_param_shard, new_state_shard)."""
    import jax
    import jax.numpy as jnp

    # Cross-rank dedup: per-rank slices are deduplicated locally, but a
    # hot row touched by several RANKS appears once per toucher in the
    # gathered set — without segment-summing here, the .at[].set()
    # write-back below would keep only the LAST duplicate's update
    # (silently dropping the other ranks' gradient for exactly the rows
    # embeddings share most).
    gathered = gathered.deduplicate()
    idx = np.asarray(gathered.indices)
    mask = (idx >= lo) & (idx < hi)
    local_idx = jnp.asarray(idx[mask] - lo)
    local_vals = jnp.asarray(np.asarray(gathered.values)[mask])
    if int(local_idx.shape[0]) == 0:
        return param_shard, state_shard

    def take_rows(leaf):
        leaf = jnp.asarray(leaf)
        if leaf.ndim and leaf.shape[0] == param_shard.shape[0]:
            return leaf[local_idx]
        return leaf  # scalar state (count) applies as-is

    def put_rows(shard_leaf, row_leaf):
        shard_leaf = jnp.asarray(shard_leaf)
        if shard_leaf.ndim and shard_leaf.shape[0] == \
                param_shard.shape[0]:
            return shard_leaf.at[local_idx].set(row_leaf)
        return row_leaf

    rows = jnp.asarray(param_shard)[local_idx]
    row_state = jax.tree.map(take_rows, state_shard)
    updates, new_row_state = opt.update(local_vals, row_state, rows)
    new_rows = rows + updates
    new_param = jnp.asarray(param_shard).at[local_idx].set(new_rows)
    new_state = jax.tree.map(put_rows, state_shard, new_row_state)
    return new_param, new_state
