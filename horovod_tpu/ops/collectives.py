"""Eager named-tensor collective API.

Mirrors the reference's handle-based async API (reference:
horovod/torch/mpi_ops.py:107-976) with JAX arrays. Arrays are immutable, so
the reference's in-place variants (``allreduce_`` etc.) are aliases that
return the new array.

Input conventions by runtime mode (see basics.py):

- ``spmd``: the tensor is this process's local value — Horovod-identical.
- ``single`` (single-controller TPU): the tensor carries every virtual
  rank's value stacked along a leading axis of length ``size()``; outputs
  are stacked the same way. For ragged per-rank shapes (allgather), pass a
  list of per-rank arrays instead.
"""

import os
import threading

import jax.numpy as jnp

from .. import basics
from ..coordinator import Handle, TensorEntry
from ..process_sets import global_process_set
from ..utils import envparse
from ..utils.callsite import user_frame
from . import reduce_ops
from .compression import Compression

_name_counter = [0]
_site_counters = {}
_name_lock = threading.Lock()
_legacy_names = None  # resolved lazily so tests can set the env first


def _auto_name(kind):
    """Deterministic per-call-site auto name.

    The reference names unnamed tensors by a process-global counter
    (reference: horovod/torch/mpi_ops.py _make_function handle naming).
    A global counter diverges across ranks the moment submission
    interleaving differs (two threads, a rank-local extra collective),
    and then negotiation pairs the wrong tensors or stalls — hvd-lint
    rule HVD203. Instead: name by the *user call-site*
    (file:qualname:lineno) plus a per-site counter, which is identical
    on every rank running the same program regardless of interleaving
    between sites. HOROVOD_TPU_LEGACY_AUTO_NAMES=1 restores the old
    global-counter scheme.
    """
    global _legacy_names
    if _legacy_names is None:
        _legacy_names = envparse.get_bool(envparse.LEGACY_AUTO_NAMES)
    if _legacy_names:
        with _name_lock:
            _name_counter[0] += 1
            return f"{kind}.noname.{_name_counter[0]}"
    filename, lineno, qualname = user_frame(skip=2)
    # basename, not the full path: venv/checkout prefixes legally differ
    # across hosts of one job; the script's own name does not.
    module = os.path.basename(filename)
    if module.endswith(".py"):
        module = module[:-3]
    key = (kind, filename, lineno)
    with _name_lock:
        count = _site_counters.get(key, 0) + 1
        _site_counters[key] = count
    return f"{kind}.auto.{module}:{qualname}:{lineno}#{count}"


def reset_auto_name_counters():
    """Reset per-site auto-name counters (elastic restarts re-run the
    program from a known point; counters must restart with it so ranks
    that rejoin agree on names)."""
    global _legacy_names
    with _name_lock:
        _site_counters.clear()
        _name_counter[0] = 0
        _legacy_names = None


def _submit(entry):
    rt = basics.runtime()
    rt.check_alive()
    return rt.coordinator.submit(entry)


def _check_stacked(tensor, process_set, kind):
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        n = len(process_set.ranks)
        if tensor.ndim == 0 or tensor.shape[0] != n:
            raise ValueError(
                f"{kind}: in single-controller mode the input must be "
                f"stacked with leading axis == process set size ({n}); got "
                f"shape {tensor.shape}. Each slice i is virtual rank i's "
                "tensor.")


# --------------------------------------------------------------------------
# allreduce
# --------------------------------------------------------------------------
def allreduce_async(tensor, average=None, name=None, op=None,
                    prescale_factor=1.0, postscale_factor=1.0,
                    process_set=global_process_set, codec=None):
    """Async allreduce; returns a Handle (reference:
    horovod/torch/mpi_ops.py:154). ``codec`` is the wire-codec name a
    quantizing compressor stamps (``Compression.int8.wire_codec``) —
    the collective itself runs the quantized pipeline, so the marker
    must travel with the entry rather than transform the tensor."""
    op = reduce_ops.handle_average_backwards_compatibility(op, average)
    tensor = jnp.asarray(tensor)
    _check_stacked(tensor, process_set, "allreduce")
    entry = TensorEntry(name or _auto_name("allreduce"), "allreduce",
                        [tensor], process_set, op=op,
                        prescale=prescale_factor, postscale=postscale_factor,
                        codec=codec)
    return _submit(entry)


def allreduce(tensor, average=None, name=None, compression=Compression.none,
              op=None, prescale_factor=1.0, postscale_factor=1.0,
              process_set=global_process_set):
    """Blocking allreduce (reference: horovod/torch/mpi_ops.py:211)."""
    tensor = jnp.asarray(tensor)
    compressed, ctx = compression.compress(tensor)
    handle = allreduce_async(compressed, average, name, op, prescale_factor,
                             postscale_factor, process_set,
                             codec=getattr(compression, "wire_codec", None))
    return compression.decompress(synchronize(handle), ctx)


# JAX arrays are immutable: the reference's in-place spellings return the
# reduced array (reference: horovod/torch/mpi_ops.py:255,290).
allreduce_async_ = allreduce_async
allreduce_ = allreduce


def fusion_buckets(n, k):
    """Split n gradient/tensor slots into k contiguous near-even fusion
    buckets (the reference's num_groups split, reference:
    horovod/tensorflow/__init__.py:627+); k<=0 means one bucket. Shared
    by the TF and keras bindings so both sync planes split identically."""
    if not k or k <= 0 or n == 0:
        return [list(range(n))]
    k = min(int(k), n)
    size, extra = divmod(n, k)
    out, start = [], 0
    for j in range(k):
        end = start + size + (1 if j < extra else 0)
        out.append(list(range(start, end)))
        start = end
    return out


def _empty_group_handle(kind):
    """Completed no-op handle for an empty group: an empty bucket must
    never reach the coordinator (fused execution indexes arrays[0]).
    Still checks runtime liveness (runtime() raises both before init()
    and after shutdown()) so a dynamically-empty bucket cannot mask a
    dead runtime."""
    basics.runtime()
    h = Handle(_auto_name(f"{kind}.empty"))
    h._complete([])
    return h


def grouped_allreduce_async(tensors, average=None, name=None, op=None,
                            prescale_factor=1.0, postscale_factor=1.0,
                            process_set=global_process_set, codec=None):
    """Grouped allreduce: the group is fused atomically — one compiled
    collective for all tensors (reference: horovod/torch/mpi_ops.py:375 +
    group_table.cc semantics)."""
    op = reduce_ops.handle_average_backwards_compatibility(op, average)
    arrays = [jnp.asarray(t) for t in tensors]
    if not arrays:
        return _empty_group_handle("grouped_allreduce")
    for a in arrays:
        _check_stacked(a, process_set, "grouped_allreduce")
    entry = TensorEntry(name or _auto_name("grouped_allreduce"), "allreduce",
                        arrays, process_set, op=op,
                        prescale=prescale_factor, postscale=postscale_factor,
                        codec=codec)
    return _submit(entry)


def grouped_allreduce(tensors, average=None, name=None,
                      compression=Compression.none, op=None,
                      prescale_factor=1.0, postscale_factor=1.0,
                      process_set=global_process_set):
    compressed, ctxs = [], []
    for t in tensors:
        c, ctx = compression.compress(jnp.asarray(t))
        compressed.append(c)
        ctxs.append(ctx)
    handle = grouped_allreduce_async(compressed, average, name, op,
                                     prescale_factor, postscale_factor,
                                     process_set,
                                     codec=getattr(compression,
                                                   "wire_codec", None))
    outputs = synchronize(handle)
    if not isinstance(outputs, (list, tuple)):
        outputs = [outputs]
    return [compression.decompress(o, ctx)
            for o, ctx in zip(outputs, ctxs)]


grouped_allreduce_async_ = grouped_allreduce_async
grouped_allreduce_ = grouped_allreduce


# --------------------------------------------------------------------------
# allgather
# --------------------------------------------------------------------------
def allgather_async(tensor, name=None, process_set=global_process_set):
    """Async allgather (reference: horovod/torch/mpi_ops.py:596). In
    single-controller mode pass a list of per-rank arrays for ragged
    first-dim gathering."""
    rt = basics.runtime()
    if isinstance(tensor, (list, tuple)):
        if rt.mode != basics.MODE_SINGLE:
            raise ValueError("List input to allgather is only meaningful in "
                             "single-controller mode")
        arrays = [jnp.asarray(t) for t in tensor]
        if len(arrays) != len(process_set.ranks):
            raise ValueError(
                f"allgather list input must have one tensor per rank "
                f"({len(process_set.ranks)}), got {len(arrays)}")
        entry = TensorEntry(name or _auto_name("allgather"), "allgather",
                            arrays, process_set, uneven=True)
    else:
        tensor = jnp.asarray(tensor)
        _check_stacked(tensor, process_set, "allgather")
        entry = TensorEntry(name or _auto_name("allgather"), "allgather",
                            [tensor], process_set)
    return _submit(entry)


def allgather(tensor, name=None, process_set=global_process_set):
    return synchronize(allgather_async(tensor, name, process_set))


def grouped_allgather_async(tensors, name=None,
                            process_set=global_process_set):
    arrays = [jnp.asarray(t) for t in tensors]
    if not arrays:
        return _empty_group_handle("grouped_allgather")
    for a in arrays:
        _check_stacked(a, process_set, "grouped_allgather")
    entry = TensorEntry(name or _auto_name("grouped_allgather"), "allgather",
                        arrays, process_set)
    return _submit(entry)


def grouped_allgather(tensors, name=None, process_set=global_process_set):
    out = synchronize(grouped_allgather_async(tensors, name, process_set))
    return list(out) if isinstance(out, (list, tuple)) else [out]


# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------
def broadcast_async(tensor, root_rank, name=None,
                    process_set=global_process_set):
    """Async broadcast from root_rank (reference:
    horovod/torch/mpi_ops.py:685)."""
    tensor = jnp.asarray(tensor)
    _check_stacked(tensor, process_set, "broadcast")
    # root_rank is a GLOBAL rank (reference semantics: process-set
    # collectives name roots by global rank); backends receive the
    # set-local index.
    if root_rank not in process_set.ranks:
        raise ValueError(
            f"root_rank {root_rank} is not a member of process set "
            f"{process_set.ranks}")
    local_root = process_set.ranks.index(root_rank)
    entry = TensorEntry(name or _auto_name("broadcast"), "broadcast",
                        [tensor], process_set, root_rank=local_root)
    return _submit(entry)


def broadcast(tensor, root_rank, name=None, process_set=global_process_set):
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


broadcast_async_ = broadcast_async
broadcast_ = broadcast


# --------------------------------------------------------------------------
# alltoall
# --------------------------------------------------------------------------
def alltoall_async(tensor, splits=None, name=None,
                   process_set=global_process_set):
    """Async alltoall (reference: horovod/torch/mpi_ops.py:824). ``splits``
    partitions dim 0 per destination rank; in single-controller mode a
    (n, n) matrix gives each virtual rank its own splits row."""
    tensor = jnp.asarray(tensor)
    _check_stacked(tensor, process_set, "alltoall")
    entry = TensorEntry(name or _auto_name("alltoall"), "alltoall",
                        [tensor], process_set, splits=splits)
    return _submit(entry)


def alltoall(tensor, splits=None, name=None,
             process_set=global_process_set):
    """Blocking alltoall; returns output or (output, received_splits) when
    splits was provided (reference: horovod/torch/mpi_ops.py:880)."""
    out, recv_splits = synchronize(
        alltoall_async(tensor, splits, name, process_set))
    if splits is None:
        return out
    return out, recv_splits


# --------------------------------------------------------------------------
# reducescatter
# --------------------------------------------------------------------------
def reducescatter_async(tensor, op=reduce_ops.Average, name=None,
                        process_set=global_process_set):
    """Async reduce-scatter (reference: horovod/tensorflow reducescatter +
    ReducescatterOp in ops/collective_operations.cc).

    Single-controller output shape: when dim0 of the per-rank tensor divides
    evenly by the set size the result is stacked (n, s0/n, ...); otherwise
    ranks receive unequal chunks (earlier ranks take the remainder, matching
    the reference) and the result is a list of n per-rank arrays."""
    tensor = jnp.asarray(tensor)
    _check_stacked(tensor, process_set, "reducescatter")
    entry = TensorEntry(name or _auto_name("reducescatter"), "reducescatter",
                        [tensor], process_set, op=op)
    return _submit(entry)


def reducescatter(tensor, op=reduce_ops.Average, name=None,
                  process_set=global_process_set):
    return synchronize(reducescatter_async(tensor, op, name, process_set))


def grouped_reducescatter_async(tensors, op=reduce_ops.Average, name=None,
                                process_set=global_process_set):
    arrays = [jnp.asarray(t) for t in tensors]
    if not arrays:
        return _empty_group_handle("grouped_reducescatter")
    for a in arrays:
        _check_stacked(a, process_set, "grouped_reducescatter")
    entry = TensorEntry(name or _auto_name("grouped_reducescatter"),
                        "reducescatter", arrays, process_set, op=op)
    return _submit(entry)


def grouped_reducescatter(tensors, op=reduce_ops.Average, name=None,
                          process_set=global_process_set):
    out = synchronize(grouped_reducescatter_async(tensors, op, name,
                                                  process_set))
    return list(out) if isinstance(out, (list, tuple)) else [out]


# --------------------------------------------------------------------------
# barrier / join / handles
# --------------------------------------------------------------------------
def barrier(process_set=global_process_set):
    """Block until all ranks reach the barrier (reference:
    horovod/torch/mpi_ops.py:976)."""
    entry = TensorEntry(_auto_name("barrier"), "barrier", [], process_set)
    synchronize(_submit(entry))


def join(device=-1):
    """Signal this rank has no more work; returns the last joined rank
    (reference: horovod/torch/mpi_ops.py:954 + EnqueueJoin,
    horovod/common/operations.cc:1729). In single-controller mode every
    virtual rank is driven by this process, so join degenerates to a
    barrier."""
    rt = basics.runtime()
    if rt.mode == basics.MODE_SINGLE:
        barrier()
        return rt.size - 1
    if getattr(rt.backend, "drives_own_cycle", False):
        # SPMD: submit through the coordinator so the background thread
        # stays the only cycle driver; the native core pads this rank into
        # peers' collectives with zeros until everyone joins.
        entry = TensorEntry(_auto_name("join"), "join", [],
                            global_process_set)
        return synchronize(_submit(entry))
    barrier()
    return rt.size - 1


def poll(handle):
    """True when the async op backing ``handle`` completed (reference:
    horovod/torch/mpi_ops.py:914)."""
    return handle.poll()


def synchronize(handle):
    """Wait for an async op and return its result (reference:
    horovod/torch/mpi_ops.py:930)."""
    return handle.wait()
