"""Reduction-op constants.

Numeric values follow the reference's ReduceOp enum exposed through the C API
(reference: horovod/common/operations.cc horovod_reduce_op_sum/average/adasum,
horovod/torch/mpi_ops.py:78-81) extended with Min/Max/Product which the
reference exposes for its TensorFlow binding.
"""

Average = 0
Sum = 1
Adasum = 2
Min = 3
Max = 4
Product = 5

_NAMES = {
    Average: "Average",
    Sum: "Sum",
    Adasum: "Adasum",
    Min: "Min",
    Max: "Max",
    Product: "Product",
}


def op_name(op):
    return _NAMES.get(op, f"Unknown({op})")


def check_op(op):
    if op not in _NAMES:
        raise ValueError(f"Unknown reduction op: {op}")
    return op


def handle_average_backwards_compatibility(op, average):
    """Reconcile the legacy ``average=`` flag with ``op=``.

    Mirrors the reference helper (reference: horovod/common/util.py
    get_average_backwards_compatibility_fun): specifying both is an error;
    ``average=True`` maps to Average, ``average=False`` to Sum.
    """
    if op is not None:
        if average is not None:
            raise ValueError("The op parameter supersedes average. Please "
                             "provide only one of them.")
        return op
    if average is not None:
        return Average if average else Sum
    return Average
