"""Pallas TPU flash attention with log-sum-exp outputs.

This is the hot-op kernel of the framework's model zoo and the inner step of
ring attention (horovod_tpu.parallel.ring_attention). The reference framework
has no attention kernels at all (it is a communication layer; SURVEY.md §2.6)
— this kernel exists because the TPU rebuild's flagship models are
transformers and attention is where HBM bandwidth goes.

Design (MXU/VMEM-first):
- Online-softmax tiling: grid (batch*heads, q_blocks, k_blocks); the k axis
  is the innermost (sequential) grid dimension, with fp32 running max /
  denominator / accumulator in VMEM scratch that persists across k steps.
- Logits and accumulation in fp32 on the MXU (``preferred_element_type``),
  inputs bf16 or fp32.
- Global-position masking: query/key chunk offsets arrive as dynamic scalars
  (scalar-prefetch), so the same compiled kernel serves local attention and
  every step of a ring schedule (offsets are device-varying under shard_map).
- Returns (out, lse); lse makes partial results mergeable (ring attention)
  and feeds the backward pass.
- Custom VJP with two backward kernels (dk/dv by key block, dq by query
  block), the standard flash-attention backward split.

On non-TPU backends the kernels run in Pallas interpret mode, so the full
test suite exercises the exact kernel logic on the CPU mesh.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..utils import envparse
from ..utils import jax_compat

_bridge_fallback_noted = set()


def note_flash_fallback(reason):
    """One-shot warning that a bridge attention site stayed on its
    einsum lowering. Shared by the torch and TF bridges so the wording
    and dedup behavior cannot diverge."""
    if reason in _bridge_fallback_noted:
        return
    _bridge_fallback_noted.add(reason)
    import warnings
    warnings.warn(
        f"tpu_compile: attention falls back to the einsum lowering "
        f"({reason}); the Pallas flash path needs 4-D rank-consistent "
        f"q/k/v with equal head dims and a mask that is all-keep or "
        f"causal at compile time", stacklevel=3)


def bridge_flash_enabled():
    """Should the torch/TF bridges route attention through this kernel?
    auto = only when the math actually runs on a TPU (in interpret mode
    the kernel is a python-level grid loop — correct but slow, so the
    CPU test suite keeps the einsum lowerings unless it opts in via
    HVDTPU_BRIDGE_FLASH=always)."""
    mode = envparse.get_str(envparse.BRIDGE_FLASH, "auto").lower()
    if mode == "always":
        return True
    if mode == "never":
        return False
    return jax.default_backend() == "tpu"

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_LANE = 128          # TPU lane width: scratch vectors are (block, _LANE)
_NEG_INF = -1e30


def _interpret():
    return jax.default_backend() != "tpu"


def _struct(shape, dtype, *like):
    """ShapeDtypeStruct carrying the union of the inputs' varying-mesh-axes
    type so pallas_call type-checks inside shard_map (check_vma)."""
    try:
        vma = frozenset().union(*(jax.typeof(x).vma for x in like))
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):
        return jax.ShapeDtypeStruct(shape, dtype)


def _pad_to(x, multiple, axis):
    size = x.shape[axis]
    pad = (-size) % multiple
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------

def _block_skip(causal, q_start, k_start, kv_len, qb, kb, block_q,
                block_k):
    """True when the (qb, kb) tile contributes nothing: every key col is
    padding, or (causal) the whole tile lies above the diagonal. Skipped
    tiles are mathematically identity updates (p==0 everywhere), so
    guarding them with pl.when drops ~half the FLOPs of a causal kernel
    without changing results."""
    skip = kb * block_k >= kv_len
    if causal:
        max_row = q_start + qb * block_q + block_q - 1
        min_col = k_start + kb * block_k
        skip = jnp.logical_or(skip, max_row < min_col)
    return skip


def _tile_interior(causal, q_start, k_start, kv_len, qb, kb, block_q,
                   block_k):
    """True when NO element of the (qb, kb) tile is masked: every key
    col is valid and (causal) the whole tile lies on/below the
    diagonal. Such tiles skip the iota/compare/where mask construction
    — per-element VPU work comparable to the exp itself, and at long
    context most tiles are interior."""
    inside = (kb + 1) * block_k <= kv_len
    if causal:
        min_row = q_start + qb * block_q
        max_col = k_start + kb * block_k + block_k - 1
        inside = jnp.logical_and(inside, max_col <= min_row)
    return inside


def _keep_scale(dm_ref, dropout_rate):
    """fp32 dropout multiplier for the current tile: keep-mask rescaled
    by 1/(1-rate). One definition keeps the four fwd/bwd use sites in
    exact sync (a fwd/bwd mismatch would be a silent gradient bug)."""
    return dm_ref[0].astype(jnp.float32) * (1.0 / (1.0 - dropout_rate))


def _seeded_keep_scale(lens_ref, qb, kb, block_q, block_k, dropout_rate):
    """fp32 dropout multiplier drawn from the ON-CHIP prng (TPU only):
    seeded per (batch·head, q-tile, k-tile), so the forward and both
    backward kernels regenerate the exact same keep pattern without a
    single byte of mask leaving VMEM — no bernoulli host program, no
    O(S²) mask residual. The threshold compare gives keep probability
    exact to 2^-32.

    Mosaic accepts at most TWO seed words: the batch·head index folds
    into the user seed via an odd multiplicative hash (a bijection mod
    2^32, so distinct bh stay distinct), and the tile coordinates pack
    into the second word (16 bits each — tile counts beyond 65536 would
    mean a >8M-token sequence)."""
    bh = pl.program_id(0)
    s1 = jnp.bitwise_xor(lens_ref[3], bh * jnp.int32(-1640531527))
    s2 = qb * jnp.int32(65536) + kb
    pltpu.prng_seed(s1, s2)
    bits = pltpu.prng_random_bits((block_q, block_k))
    bits = jax.lax.bitcast_convert_type(bits, jnp.uint32)
    thresh = jnp.uint32(int((1.0 - dropout_rate) * 4294967296.0))
    return (bits < thresh).astype(jnp.float32) * (
        1.0 / (1.0 - dropout_rate))


def _fwd_kernel(lens_ref, q_ref, k_ref, v_ref, *rest, sm_scale, causal,
                block_q, block_k, n_k, dropout_rate=0.0, seeded=False):
    # rest = [dm_ref?], o_ref, lse_ref, m_scr, l_scr, acc_scr
    if dropout_rate > 0.0 and not seeded:
        dm_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        dm_ref = None
        o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    kb = pl.program_id(2)
    qb = pl.program_id(1)
    q_start = lens_ref[0]
    k_start = lens_ref[1]
    kv_len = lens_ref[2]

    @pl.when(kb == 0)
    def _():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    skip = _block_skip(causal, q_start, k_start, kv_len, qb, kb,
                       block_q, block_k)
    interior = _tile_interior(causal, q_start, k_start, kv_len, qb, kb,
                              block_q, block_k)

    def tile_update(masked):
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        mask = None
        if masked:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = cols < kv_len      # mask key padding
            if causal:
                mask = jnp.logical_and(
                    mask, (q_start + rows) >= (k_start + cols))
            s = jnp.where(mask, s, _NEG_INF)

        m_prev = m_scr[:, :1]         # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)        # (block_q, block_k) fp32
        if mask is not None:
            # Fully-masked rows: m_new stays _NEG_INF and p would be
            # exp(0)=1 — zero those contributions so l stays 0 for them.
            p = jnp.where(mask, p, 0.0)

        l_prev = l_scr[:, :1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # Attention dropout (torch semantics: probs are dropped AFTER
        # softmax, so the normalizer l uses the undropped p while the
        # value accumulation uses the dropped/rescaled weights).
        pv = p
        if dropout_rate > 0.0 and seeded:
            pv = p * _seeded_keep_scale(lens_ref, qb, kb, block_q,
                                        block_k, dropout_rate)
        elif dm_ref is not None:
            pv = p * _keep_scale(dm_ref, dropout_rate)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            pv.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(jnp.logical_and(jnp.logical_not(skip), interior))
    def _():
        tile_update(False)

    @pl.when(jnp.logical_and(jnp.logical_not(skip),
                             jnp.logical_not(interior)))
    def _():
        tile_update(True)

    @pl.when(kb == n_k - 1)
    def _():
        l = l_scr[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[:] / safe_l).astype(o_ref.dtype)
        m = m_scr[:, 0]
        lse = jnp.where(l_scr[:, 0] == 0.0, _NEG_INF,
                        m + jnp.log(l_scr[:, 0]))
        # lse is laid out (bh, 1, sq): TPU requires the last two block dims
        # to divide (8, 128) or equal the array dims — (1, 1, block_q) does.
        lse_ref[0, 0] = lse.astype(lse_ref.dtype)


def _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k,
              dm=None, dropout_rate=0.0, seeded=False):
    bh, sq, d = q.shape
    sk = k.shape[1]
    n_q = sq // block_q
    n_k = sk // block_k
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, n_k=n_k,
        dropout_rate=dropout_rate, seeded=seeded)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
    ]
    operands = [q, k, v]
    if dropout_rate > 0.0 and not seeded:
        in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), lambda b, i, j, lens: (b, i, j)))
        operands.append(dm)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_q, n_k),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j, lens: (b, 0, i)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, _LANE), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    out_shapes = [
        _struct((bh, sq, d), q.dtype, q, k, v, lens),
        _struct((bh, 1, sq), jnp.float32, q, k, v, lens),
    ]
    compiler_params = jax_compat.tpu_compiler_params(
        ("parallel", "parallel", "arbitrary"))
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(lens, *operands)
    return o, lse[:, 0, :]


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------

def _bwd_dkv_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, *rest, sm_scale, causal, block_q,
                    block_k, n_q, dropout_rate=0.0, seeded=False):
    # rest = [dm_ref?], dk_ref, dv_ref, dk_scr, dv_scr
    if dropout_rate > 0.0 and not seeded:
        dm_ref, dk_ref, dv_ref, dk_scr, dv_scr = rest
    else:
        dm_ref = None
        dk_ref, dv_ref, dk_scr, dv_scr = rest
    qb = pl.program_id(2)
    kb = pl.program_id(1)
    q_start = lens_ref[0]
    k_start = lens_ref[1]
    kv_len = lens_ref[2]

    @pl.when(qb == 0)
    def _():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    skip = _block_skip(causal, q_start, k_start, kv_len, qb, kb,
                       block_q, block_k)
    interior = _tile_interior(causal, q_start, k_start, kv_len, qb, kb,
                              block_q, block_k)

    def tile_update(masked):
        q = q_ref[0]                  # (block_q, d)
        k = k_ref[0]                  # (block_k, d)
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]           # (block_q,)
        delta = delta_ref[0, 0]       # (block_q,)

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # (bq, bk)
        if masked:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(
                    mask, (q_start + rows) >= (k_start + cols))
            p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        else:
            # Interior tile: no element masked (see _tile_interior).
            p = jnp.exp(s - lse[:, None])        # (bq, bk) fp32

        # Dropout backward: o = (P∘M̃)V with M̃ = mask/(1-rate), so
        # dV = (P∘M̃)ᵀdO and dP = (dO Vᵀ)∘M̃; the delta trick survives
        # because Σₖ Pᵢₖ dPᵢₖ = rowsum(dO∘O) = delta exactly as without
        # dropout (O already carries M̃).
        pv = p
        keep = None
        if dropout_rate > 0.0 and seeded:
            keep = _seeded_keep_scale(lens_ref, qb, kb, block_q,
                                      block_k, dropout_rate)
            pv = p * keep
        elif dm_ref is not None:
            keep = _keep_scale(dm_ref, dropout_rate)
            pv = p * keep
        # MXU operands in the input dtype (bf16 in training; identity for
        # fp32 inputs), fp32 accumulation. fp32 operands would run the
        # matmuls at a fraction of MXU rate — the softmax weights and ds
        # are the canonical safe-to-round tensors of the flash backward.
        dv_scr[:] = dv_scr[:] + jax.lax.dot_general(
            pv.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # (bq, bk)
        if keep is not None:
            dp = dp * keep
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_scr[:] = dk_scr[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(jnp.logical_not(skip), interior))
    def _():
        tile_update(False)

    @pl.when(jnp.logical_and(jnp.logical_not(skip),
                             jnp.logical_not(interior)))
    def _():
        tile_update(True)

    @pl.when(qb == n_q - 1)
    def _():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(lens_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, *rest, sm_scale, causal, block_q,
                   block_k, n_k, dropout_rate=0.0, seeded=False):
    # rest = [dm_ref?], dq_ref, dq_scr
    if dropout_rate > 0.0 and not seeded:
        dm_ref, dq_ref, dq_scr = rest
    else:
        dm_ref = None
        dq_ref, dq_scr = rest
    kb = pl.program_id(2)
    qb = pl.program_id(1)
    q_start = lens_ref[0]
    k_start = lens_ref[1]
    kv_len = lens_ref[2]

    @pl.when(kb == 0)
    def _():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    skip = _block_skip(causal, q_start, k_start, kv_len, qb, kb,
                       block_q, block_k)
    interior = _tile_interior(causal, q_start, k_start, kv_len, qb, kb,
                              block_q, block_k)

    def tile_update(masked):
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            rows = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            mask = cols < kv_len
            if causal:
                mask = jnp.logical_and(
                    mask, (q_start + rows) >= (k_start + cols))
            p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        else:
            p = jnp.exp(s - lse[:, None])  # interior: nothing masked
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if dropout_rate > 0.0 and seeded:
            dp = dp * _seeded_keep_scale(lens_ref, qb, kb, block_q,
                                         block_k, dropout_rate)
        elif dm_ref is not None:
            dp = dp * _keep_scale(dm_ref, dropout_rate)
        ds = p * (dp - delta[:, None]) * sm_scale
        # input-dtype operand, fp32 accumulation (see _bwd_dkv_kernel).
        dq_scr[:] = dq_scr[:] + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(jnp.logical_and(jnp.logical_not(skip), interior))
    def _():
        tile_update(False)

    @pl.when(jnp.logical_and(jnp.logical_not(skip),
                             jnp.logical_not(interior)))
    def _():
        tile_update(True)

    @pl.when(kb == n_k - 1)
    def _():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_call(q, k, v, o, do, lse, lens, sm_scale, causal, block_q, block_k,
              g_lse=None, dm=None, dropout_rate=0.0, seeded=False):
    bh, sq, d = q.shape
    sk = k.shape[1]
    n_q = sq // block_q
    n_k = sk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                        # (bh, sq)
    if g_lse is not None:
        # dlse_i/ds_ij = p_ij, so the lse cotangent enters the shared
        # ds = p*(dp - delta')*scale term as delta' = delta - g_lse.
        delta = delta - g_lse.astype(jnp.float32)
    # 3-D (bh, 1, sq) layout for TPU block-shape rules (see _fwd_kernel).
    lse3 = lse[:, None, :]
    delta3 = delta[:, None, :]

    compiler_params = jax_compat.tpu_compiler_params(
        ("parallel", "parallel", "arbitrary"))

    dkv_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i, lens: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i, lens: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i, lens: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i, lens: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i, lens: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, j, i, lens: (b, 0, i)),
    ]
    dkv_operands = [q, k, v, do, lse3, delta3]
    if dropout_rate > 0.0 and not seeded:
        dkv_in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), lambda b, j, i, lens: (b, i, j)))
        dkv_operands.append(dm)
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_k, n_q),
        in_specs=dkv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i, lens: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i, lens: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q,
                          dropout_rate=dropout_rate, seeded=seeded),
        grid_spec=dkv_spec,
        out_shape=[
            _struct((bh, sk, d), k.dtype, q, k, v, do, lens),
            _struct((bh, sk, d), v.dtype, q, k, v, do, lens),
        ],
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(lens, *dkv_operands)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j, lens: (b, j, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j, lens: (b, 0, i)),
        pl.BlockSpec((1, 1, block_q), lambda b, i, j, lens: (b, 0, i)),
    ]
    dq_operands = [q, k, v, do, lse3, delta3]
    if dropout_rate > 0.0 and not seeded:
        dq_in_specs.append(pl.BlockSpec(
            (1, block_q, block_k), lambda b, i, j, lens: (b, i, j)))
        dq_operands.append(dm)
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_q, n_k),
        in_specs=dq_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j, lens: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    (dq,) = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_k=n_k,
                          dropout_rate=dropout_rate, seeded=seeded),
        grid_spec=dq_spec,
        out_shape=[_struct((bh, sq, d), q.dtype, q, k, v, do, lens)],
        compiler_params=compiler_params,
        interpret=_interpret(),
    )(lens, *dq_operands)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Differentiable public entry points
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, lens, sm_scale, causal, block_q, block_k):
    o, _ = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k)
    return o


def _flash_fwd(q, k, v, lens, sm_scale, causal, block_q, block_k):
    o, lse = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse, lens)


def _flash_bwd(sm_scale, causal, block_q, block_k, res, g):
    q, k, v, o, lse, lens = res
    dq, dk, dv = _bwd_call(q, k, v, o, g, lse, lens, sm_scale, causal,
                           block_q, block_k)
    dlens = np.zeros((3,), jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_with_lse(q, k, v, lens, sm_scale, causal, block_q, block_k):
    return _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k)


def _flash_with_lse_fwd(q, k, v, lens, sm_scale, causal, block_q, block_k):
    o, lse = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k)
    return (o, lse), (q, k, v, o, lse, lens)


def _flash_with_lse_bwd(sm_scale, causal, block_q, block_k, res, g):
    q, k, v, o, lse, lens = res
    go, g_lse = g
    dq, dk, dv = _bwd_call(q, k, v, o, go, lse, lens, sm_scale, causal,
                           block_q, block_k, g_lse=g_lse)
    dlens = np.zeros((3,), jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash_dropout(q, k, v, lens, dm, sm_scale, causal, block_q, block_k,
                   rate):
    o, _ = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k,
                     dm=dm, dropout_rate=rate)
    return o


def _flash_dropout_fwd(q, k, v, lens, dm, sm_scale, causal, block_q,
                       block_k, rate):
    o, lse = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k,
                       dm=dm, dropout_rate=rate)
    return o, (q, k, v, o, lse, lens, dm)


def _flash_dropout_bwd(sm_scale, causal, block_q, block_k, rate, res, g):
    q, k, v, o, lse, lens, dm = res
    dq, dk, dv = _bwd_call(q, k, v, o, g, lse, lens, sm_scale, causal,
                           block_q, block_k, dm=dm, dropout_rate=rate)
    dlens = np.zeros((3,), jax.dtypes.float0)
    ddm = np.zeros(dm.shape, jax.dtypes.float0)
    return dq, dk, dv, dlens, ddm


_flash_dropout.defvjp(_flash_dropout_fwd, _flash_dropout_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_seeded(q, k, v, lens, sm_scale, causal, block_q, block_k,
                  rate):
    o, _ = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k,
                     dropout_rate=rate, seeded=True)
    return o


def _flash_seeded_fwd(q, k, v, lens, sm_scale, causal, block_q, block_k,
                      rate):
    o, lse = _fwd_call(q, k, v, lens, sm_scale, causal, block_q, block_k,
                       dropout_rate=rate, seeded=True)
    return o, (q, k, v, o, lse, lens)


def _flash_seeded_bwd(sm_scale, causal, block_q, block_k, rate, res, g):
    q, k, v, o, lse, lens = res
    dq, dk, dv = _bwd_call(q, k, v, o, g, lse, lens, sm_scale, causal,
                           block_q, block_k, dropout_rate=rate,
                           seeded=True)
    dlens = np.zeros((4,), jax.dtypes.float0)
    return dq, dk, dv, dlens


_flash_seeded.defvjp(_flash_seeded_fwd, _flash_seeded_bwd)


def _prepare(q, k, v, block_q, block_k):
    """Reshape (B,H,S,D)→(BH,S,D), pad D to a lane tile (64 when D<=64,
    else 128) and S to block multiples. Returns padded tensors +
    original dims."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    # Clamp requested blocks to the (pow2-rounded) sequence lengths; the
    # caller may ask for >128 tiles (bigger s-tiles amortize the online-
    # softmax bookkeeping at long context — see docs/PERF.md sweep).
    block_q = min(block_q, max(8, 1 << (sq - 1).bit_length()))
    block_k = min(block_k, max(8, 1 << (sk - 1).bit_length()))

    def flat(x):
        return x.reshape((b * h,) + x.shape[2:])

    # Head dims <=64 stay at 64 lanes: Mosaic supports 64-wide last dims,
    # and padding d=64 heads to 128 would double both the matmul work and
    # the HBM traffic of every block (~10% kernel time at seq 512,
    # docs/PERF.md round-3 sweep).
    d_pad = 64 if d <= 64 else _LANE
    q, k, v = flat(q), flat(k), flat(v)
    q = _pad_to(_pad_to(q, d_pad, 2), block_q, 1)
    k = _pad_to(_pad_to(k, d_pad, 2), block_k, 1)
    v = _pad_to(_pad_to(v, d_pad, 2), block_k, 1)
    return q, k, v, (b, h, sq, sk, d), block_q, block_k


def _varying(*xs):
    """True when any input is device-varying under shard_map (vma)."""
    try:
        return bool(frozenset().union(
            *(jax.typeof(x).vma for x in xs if hasattr(x, "dtype")
              or not np.isscalar(x))))
    except (AttributeError, TypeError):
        # Pre-varying-types jax: no vma on avals. Any named axis in the
        # tracing env means we are inside a shard_map/pmap body, where
        # interpret-mode pallas_call has no replication rule — treat it
        # as varying so the caller takes the einsum fallback.
        return jax_compat.inside_named_axis()


def flash_attention(q, k, v, *, causal=False, sm_scale=None,
                    q_offset=0, k_offset=0, kv_len=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    with_lse=False, dropout_mask=None, dropout_rate=0.0,
                    dropout_seed=None):
    """Flash attention over (batch, heads, seq, head_dim) tensors.

    Args:
      causal: apply a causal mask in *global* coordinates:
        position(q) = q_offset + row, position(k) = k_offset + col. Offsets
        may be traced scalars (device-varying under shard_map) — this is what
        lets one compiled kernel serve every ring-attention step.
      kv_len: number of valid keys in ``k`` (defaults to its length);
        keys at or beyond this index are masked (padding).
      with_lse: also return the per-query log-sum-exp (fp32, (B,H,Sq)).
      dropout_mask: optional (B, H, Sq, Sk) keep-mask applied to the
        softmax probabilities (torch attention-dropout semantics: probs
        are dropped after normalization and the kept ones rescaled by
        1/(1-dropout_rate)). Passing the mask explicitly — rather than a
        PRNG seed — keeps the kernel exactly reproducible against the
        einsum oracle; the torch/TF bridges generate it with
        jax.random.bernoulli per attention site.
      dropout_rate: the rate the mask was drawn with (for rescaling).
      dropout_seed: TPU-only alternative to dropout_mask — an int32
        scalar (may be traced) seeding the ON-CHIP prng; the keep
        pattern is regenerated per tile inside the forward and both
        backward kernels, so no mask is ever materialized in HBM (no
        bernoulli program, no O(S²) residual). Unsupported in interpret
        mode (pltpu prng has no CPU lowering) — callers on CPU use
        dropout_mask instead.
    """
    orig_dtype = q.dtype
    b, h, sq, d = q.shape
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if kv_len is None:
        kv_len = k.shape[2]
    if dropout_seed is not None and dropout_mask is not None:
        raise ValueError(
            "flash_attention: pass dropout_mask OR dropout_seed, not both")
    has_dropout = (dropout_mask is not None or dropout_seed is not None) \
        and dropout_rate > 0.0
    if has_dropout and with_lse:
        raise NotImplementedError(
            "flash_attention: dropout with with_lse is unsupported "
            "(ring/merged attention never uses attention dropout)")
    if dropout_seed is not None and dropout_rate > 0.0 and _interpret():
        raise NotImplementedError(
            "flash_attention: dropout_seed needs the on-chip prng "
            "(pltpu) — unavailable in interpret mode; pass an explicit "
            "dropout_mask on CPU")
    if _interpret() and _varying(q, k, v, q_offset, k_offset):
        # Pallas's HLO interpreter cannot run with device-varying operands
        # inside shard_map (check_vma dynamic_slice limitation); on non-TPU
        # backends use the einsum oracle there. On TPU the compiled kernel
        # handles shard_map natively.
        return reference_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, q_offset=q_offset,
            k_offset=k_offset, kv_len=kv_len, with_lse=with_lse,
            dropout_mask=dropout_mask, dropout_rate=dropout_rate)
    qp, kp, vp, dims, bq, bk = _prepare(q, k, v, block_q, block_k)
    lens = jnp.asarray([q_offset, k_offset, kv_len], jnp.int32)
    if has_dropout and dropout_seed is not None:
        lens4 = jnp.concatenate(
            [lens, jnp.asarray(dropout_seed, jnp.int32).reshape(1)])
        o = _flash_seeded(qp, kp, vp, lens4, float(sm_scale),
                          bool(causal), bq, bk, float(dropout_rate))
        return o[:, :sq, :d].reshape(b, h, sq, d).astype(orig_dtype)
    if has_dropout:
        # bf16 carries 0/1 exactly at half the HBM traffic of fp32.
        dm = dropout_mask.astype(jnp.bfloat16).reshape(b * h, sq, -1)
        dm = _pad_to(_pad_to(dm, bk, 2), bq, 1)
        o = _flash_dropout(qp, kp, vp, lens, dm, float(sm_scale),
                           bool(causal), bq, bk, float(dropout_rate))
        return o[:, :sq, :d].reshape(b, h, sq, d).astype(orig_dtype)
    if with_lse:
        o, lse = _flash_with_lse(qp, kp, vp, lens, float(sm_scale),
                                 bool(causal), bq, bk)
        o = o[:, :sq, :d].reshape(b, h, sq, d).astype(orig_dtype)
        lse = lse[:, :sq].reshape(b, h, sq)
        return o, lse
    o = _flash(qp, kp, vp, lens, float(sm_scale), bool(causal), bq, bk)
    return o[:, :sq, :d].reshape(b, h, sq, d).astype(orig_dtype)


def reference_attention(q, k, v, *, causal=False, sm_scale=None,
                        q_offset=0, k_offset=0, kv_len=None,
                        with_lse=False, dropout_mask=None,
                        dropout_rate=0.0):
    """Plain einsum attention with the same masking semantics — the
    correctness oracle for the kernel tests and the shard_map-on-CPU
    fallback. Offsets may be traced scalars."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    if sm_scale is None:
        sm_scale = 1.0 / np.sqrt(d)
    if kv_len is None:
        kv_len = sk
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * sm_scale
    cols = jnp.arange(sk)
    mask = (cols < kv_len)[None, None, None, :]
    if causal:
        rows = q_offset + jnp.arange(sq)
        cmask = rows[:, None] >= (k_offset + cols)[None, :]
        mask = jnp.logical_and(mask, cmask[None, None])
    mask = jnp.broadcast_to(mask, s.shape)
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    any_visible = jnp.any(mask, axis=-1, keepdims=True)
    p = jnp.where(mask, jnp.exp(s - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    safe_l = jnp.where(l == 0.0, 1.0, l)
    pv = p
    if dropout_mask is not None and dropout_rate > 0.0:
        # Post-softmax dropout: the normalizer l keeps the undropped sum.
        pv = p * (dropout_mask.astype(jnp.float32)
                  / (1.0 - dropout_rate))
    o = (jnp.einsum("bhqk,bhkd->bhqd", pv, v.astype(jnp.float32))
         / safe_l).astype(q.dtype)
    if not with_lse:
        return o
    lse = jnp.where(any_visible[..., 0], m[..., 0] + jnp.log(safe_l[..., 0]),
                    _NEG_INF)
    return o, lse
