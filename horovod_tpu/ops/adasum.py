"""Adasum: scale-invariant adaptive summation.

Re-implementation of the reference's Adasum reduction (reference:
horovod/common/ops/adasum/adasum.h:194-343; pairwise rule at :397-407):

    a' = (1 - dot(a,b) / (2*||a||^2)) * a  +  (1 - dot(a,b) / (2*||b||^2)) * b

applied over a binary tree of rank pairs (rank r combines with r XOR 2^t in
round t — the vector-halving distance-doubling schedule). The reference
restricts Adasum to power-of-2 rank counts
(reference: horovod/tensorflow/__init__.py:138-154); we keep that contract.

On TPU the whole tree is one jitted XLA program: in single-controller mode
the stacked operand already holds every rank's tensor, so the tree is pure
compute (XLA schedules any ICI moves); for in-jit use inside shard_map see
``adasum_axis`` which runs the same schedule with ppermute exchanges.
"""

import jax
import jax.numpy as jnp
from jax import lax


def _is_pow2(n):
    return n > 0 and (n & (n - 1)) == 0


def adasum_pair(a, b, eps=0.0):
    """Combine two gradient tensors with the Adasum rule (fp32 math,
    zero-norm guarded like the reference's CheckPointerSendRecv path)."""
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    na = jnp.sum(af * af)
    nb = jnp.sum(bf * bf)
    ca = jnp.where(na > eps, 1.0 - dot / (2.0 * jnp.maximum(na, 1e-30)), 1.0)
    cb = jnp.where(nb > eps, 1.0 - dot / (2.0 * jnp.maximum(nb, 1e-30)), 1.0)
    return (ca * af + cb * bf).astype(a.dtype)


def adasum_tree(stacked):
    """Reduce a stacked (n, ...) tensor down the VHDD pair tree; returns the
    combined tensor of shape ``stacked.shape[1:]``."""
    n = stacked.shape[0]
    if not _is_pow2(n):
        raise ValueError(
            f"Adasum requires a power-of-2 number of ranks, got {n} "
            "(reference restriction, horovod/tensorflow/__init__.py:138)")
    xs = [stacked[i] for i in range(n)]
    dist = 1
    while dist < n:
        for i in range(0, n, 2 * dist):
            xs[i] = adasum_pair(xs[i], xs[i + dist])
        dist *= 2
    return xs[0]


def adasum_pair_np(a, b):
    """Numpy float64 reference of the pairwise rule — the ONE oracle
    shared by the host-plane SPMD test, the compiled-plane tests, and
    the multichip dryrun leg (duplicating it risks the copies drifting
    on the zero-norm guard / promotion details)."""
    import numpy as np
    af = np.asarray(a, np.float64).ravel()
    bf = np.asarray(b, np.float64).ravel()
    dot = float(af @ bf)
    na = float(af @ af)
    nb = float(bf @ bf)
    ca = 1.0 - dot / (2.0 * na) if na > 0 else 1.0
    cb = 1.0 - dot / (2.0 * nb) if nb > 0 else 1.0
    return (ca * np.asarray(a, np.float64)
            + cb * np.asarray(b, np.float64))


def adasum_vhdd_np(stack):
    """Numpy pairwise VHDD tree over a list/stack of tensors."""
    import numpy as np
    xs = [np.asarray(x, np.float64) for x in stack]
    while len(xs) > 1:
        xs = [adasum_pair_np(xs[i], xs[i + 1])
              for i in range(0, len(xs), 2)]
    return xs[0]


def adasum_allreduce_stacked(backend, arrays, process_set, prescale=None,
                             postscale=None):
    """Eager stacked Adasum used by XlaSingleBackend (one jitted program per
    fusion bucket)."""
    mesh = backend._mesh(process_set)
    n = mesh.devices.size
    key = ("adasum", process_set.process_set_id)

    def build():
        def fn(scales, *xs):
            pre, post = scales
            outs = []
            for x in xs:
                if pre is not None:
                    x = x * pre.astype(x.dtype)
                y = adasum_tree(x)
                if post is not None:
                    y = y * post.astype(y.dtype)
                outs.append(jnp.broadcast_to(y[None], (n,) + y.shape))
            return tuple(outs)
        return jax.jit(fn)

    fn = backend._cached(key, build)
    pre = jnp.asarray(1.0 if prescale is None else prescale, jnp.float32)
    post = jnp.asarray(1.0 if postscale is None else postscale, jnp.float32)
    ins = tuple(backend.shard(process_set, jnp.asarray(a)) for a in arrays)
    outs = fn((pre, post), *ins)
    return [backend.shard(process_set, o) for o in outs]


def adasum_axis(x, axis_name):
    """In-jit Adasum over a mesh axis, for use inside shard_map/pjit.

    Runs the VHDD schedule with ppermute exchanges: in round t each rank
    swaps its current accumulator with partner = rank XOR 2^t and applies the
    pairwise rule. All ranks converge to the tree reduction. This is the
    compiled-data-plane analog of the reference's AdasumMPI recursive
    halving (reference: horovod/common/ops/adasum/adasum_mpi.cc).
    """
    from ..utils.jax_compat import axis_size
    n = axis_size(axis_name)
    if not _is_pow2(n):
        raise ValueError(f"Adasum requires power-of-2 axis size, got {n}")
    idx = lax.axis_index(axis_name)
    acc = x
    dist = 1
    while dist < n:
        perm = [(i, i ^ dist) for i in range(n)]
        other = lax.ppermute(acc, axis_name, perm)
        # Ordering: the lower rank of the pair is 'a', higher is 'b', so both
        # sides compute the identical (symmetric) combination.
        is_low = (idx & dist) == 0
        acc = adasum_pair(jnp.where(is_low, acc, other),
                          jnp.where(is_low, other, acc))
        dist *= 2
    return acc
