"""Gradient bucketing for comm/compute overlap (``HVDTPU_OVERLAP``).

Horovod's core performance idea is to overlap gradient communication
with the remaining backward pass: gradients are packed into fixed-size
buckets and each bucket's collective is dispatched as soon as its
members are ready, so the reduction of layer N runs under the gradient
compute of layer N-1 (reference: horovod/common/controller.cc
FuseResponses; *Densifying Assumed-sparse Tensors*, arXiv:1905.04035,
on why dense bucketed accumulation beats per-tensor dispatch).

The in-jit realization here is dependency-driven rather than
hook-driven: :func:`bucketed_reduce_axis` emits ONE collective per
bucket whose operands are only that bucket's gradient leaves. Because
backprop produces gradients in reverse layer order, a bucket holding
late-layer gradients is ready while early layers are still
differentiating — XLA's latency-hiding scheduler is then free to run
its collective under the remaining backward compute, which a single
fused all-gradient barrier (or a reduction depending on the full tree)
structurally forbids. Buckets are planned over the REVERSED leaf order
for exactly that reason: leaf trees flatten roughly first-layer-first,
so reversing approximates gradient-availability order and the first
bucket issued is the first one ready.

Numerics: splitting an elementwise collective (psum/pmean) into
per-bucket concatenated calls performs the identical per-element
cross-replica reduction, so the bucketed path is bit-identical to the
per-leaf path for Sum/Average — pinned by
tests/test_overlap.py::test_overlap_bit_exact_vs_barrier. Wire-codec
buckets (int8/fp8) quantize the CONCATENATED bucket, so quantization
blocks may span tensor boundaries; that changes rounding relative to
per-tensor quantization (never relative to OVERLAP=0 plain fp32, which
stays exact) and is documented in docs/performance.md.

Adasum is excluded: its scale-invariant combination is defined per
tensor, and concatenating tensors into one vector would change the dot
products it is built from. Callers keep Adasum on the per-leaf path.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import reduce_ops

DEFAULT_BUCKET_BYTES = 16 * 1024 * 1024


class Bucket:
    """One planned fusion bucket: leaf indices (all sharing ``dtype``)
    and the payload byte count."""

    __slots__ = ("indices", "dtype", "nbytes")

    def __init__(self, indices, dtype, nbytes):
        self.indices = indices
        self.dtype = dtype
        self.nbytes = nbytes

    def __repr__(self):
        return (f"Bucket(n={len(self.indices)}, dtype={self.dtype}, "
                f"bytes={self.nbytes})")


def plan_buckets(leaves, bucket_bytes=DEFAULT_BUCKET_BYTES, reverse=True):
    """Group leaf indices into per-dtype buckets of at most
    ``bucket_bytes`` payload (a single leaf larger than the budget gets
    its own bucket — tensors are never split). ``reverse`` walks the
    leaves last-to-first so bucket order approximates backprop
    availability order; the relative order WITHIN the returned index
    lists is always ascending, so unbucketing is a stable scatter.
    """
    bucket_bytes = max(int(bucket_bytes), 1)
    order = range(len(leaves) - 1, -1, -1) if reverse \
        else range(len(leaves))
    open_buckets = {}   # dtype -> (indices, nbytes)
    closed = []
    for i in order:
        leaf = leaves[i]
        dtype = jnp.asarray(leaf).dtype if not hasattr(leaf, "dtype") \
            else leaf.dtype
        nbytes = int(np.prod(leaf.shape)) * dtype.itemsize \
            if leaf.ndim else dtype.itemsize
        cur = open_buckets.get(str(dtype))
        if cur is not None and cur[1] + nbytes > bucket_bytes:
            closed.append(Bucket(sorted(cur[0]), dtype, cur[1]))
            cur = None
        if cur is None:
            cur = ([], 0)
        cur[0].append(i)
        open_buckets[str(dtype)] = (cur[0], cur[1] + nbytes)
    for indices, nbytes in open_buckets.values():
        dtype = leaves[indices[0]].dtype
        closed.append(Bucket(sorted(indices), dtype, nbytes))
    return closed


def _pack(leaves, bucket):
    flats = [jnp.ravel(leaves[i]) for i in bucket.indices]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def _unpack(buf, leaves, bucket, out):
    sizes = [int(np.prod(leaves[i].shape)) for i in bucket.indices]
    offset = 0
    for i, size in zip(bucket.indices, sizes):
        out[i] = lax.slice(buf, (offset,), (offset + size,)).reshape(
            leaves[i].shape)
        offset += size


def bucketed_reduce_axis(leaves, op, axis_name, *,
                         bucket_bytes=DEFAULT_BUCKET_BYTES,
                         prescale=None, postscale=None,
                         wire_codec=None, block=256):
    """Per-bucket gradient reduction over a shard_map axis.

    Plain path (``wire_codec=None``): one ``psum``/``pmean`` per bucket
    — bit-identical to the per-leaf reduction, but with per-bucket data
    dependencies the XLA scheduler can overlap with remaining backprop.
    Wire path: one EQuARX quantized pipeline per bucket
    (``quantized_allreduce_axis`` on the concatenated buffer), so both
    collective legs of every bucket ride the narrow format.

    Returns the reduced leaves in the original order.
    """
    if op not in (reduce_ops.Average, reduce_ops.Sum):
        raise ValueError(
            "bucketed_reduce_axis supports Average/Sum only (Adasum's "
            f"per-tensor combination cannot be bucketed); got "
            f"{reduce_ops.op_name(op)}")
    if not leaves:
        return []
    out = [None] * len(leaves)
    for bucket in plan_buckets(leaves, bucket_bytes):
        buf = _pack(leaves, bucket)
        if prescale is not None:
            buf = buf * jnp.asarray(prescale).astype(buf.dtype)
        if wire_codec is not None:
            from ..compression.codecs import quantized_allreduce_axis
            buf = quantized_allreduce_axis(
                buf, axis_name, codec=wire_codec, block=block,
                average=(op == reduce_ops.Average))
        elif op == reduce_ops.Average:
            buf = lax.pmean(buf, axis_name)
        else:
            buf = lax.psum(buf, axis_name)
        if postscale is not None:
            buf = buf * jnp.asarray(postscale).astype(buf.dtype)
        _unpack(buf, leaves, bucket, out)
    return out
