"""ZeRO-1 on TPU: cross-replica sharded weight update (``HVDTPU_ZERO``).

The optimizer update is the last fully-replicated stage of the data-
parallel loop: every replica holds the whole optimizer state and
redundantly computes the whole weight update. *Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training* (arXiv:2004.13336)
shows the update partitions across replicas for free — the gradient
reduction an allreduce already performs can land each replica only its
1/n slice (reduce-scatter), the optimizer steps that slice with 1/n of
the state, and the updated slice broadcasts back (allgather). Per-chip
Adam-family state drops from 2× params to 2× params / n; the two legs
move the same bytes as one allreduce (which IS reduce-scatter +
allgather in a ring/ICI formulation), so the memory win is ~free.

The plan here is the portable-collectives formulation (*Memory-
efficient array redistribution through portable collective
communication*, arXiv:2112.01075): sharding is expressed as a
deterministic pad-and-split plan over fixed fusion buckets —
:func:`plan_zero` maps (leaf shapes, world size, bucket budget,
quantization granule) to per-bucket shard geometry, so any cohort that
agrees on those inputs derives the identical plan, uneven leaf sizes
are absorbed by per-bucket padding (never by per-leaf remainders), and
a world-size change is a plan-to-plan redistribution
(:func:`reshard_state`) rather than an ad-hoc gather/scatter.

Buckets come from :func:`ops.bucketing.plan_buckets` — the same
reversed-leaf-order plans the overlap path uses — so under
``HVDTPU_OVERLAP`` semantics the first bucket emitted holds the last
(= earliest-available) gradients and XLA's latency-hiding scheduler can
run bucket k's reduce-scatter under the remaining backward pass and
bucket k's allgather under other buckets' updates.

Compression composes per bucket: wire codecs (int8/fp8,
``horovod_tpu/compression/codecs.py``) quantize BOTH legs — the
scatter leg rides the EQuARX all_to_all formulation (narrow payload,
f32 accumulate), the gather leg requantizes the updated shard — with
per-bucket error-feedback residuals carried in the sharded state.
Like the eager plane's ResidualStore, residuals never cross elastic
cohorts: a membership change reshards the moments and ZEROES the
residuals (the new cohort's shard geometry does not line up with the
old quantization debt).

Numerics contract (pinned by tests/test_zero.py): with no codec, the
sharded update is BIT-IDENTICAL to the replicated update for fp32
Sum/Average — psum_scatter performs the same per-element cross-replica
reduction as psum, elementwise optimizer transforms act per element,
and the allgather reassembles exactly.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import reduce_ops
from .bucketing import DEFAULT_BUCKET_BYTES, plan_buckets, _pack, _unpack
from ..utils import envparse
from ..utils.jax_compat import shard_map as _shard_map
from ..utils.logging_util import get_logger

#: ``HVDTPU_ZERO_BUCKET_BYTES`` default mirrors the overlap plane's
#: bucket budget — one constant to retune, not two.
DEFAULT_ZERO_BUCKET_BYTES = DEFAULT_BUCKET_BYTES


def _m_state_bytes():
    from ..telemetry import core as telemetry
    return telemetry.gauge(
        "hvd_zero_state_bytes",
        "Per-replica optimizer-state bytes under ZeRO-1 sharding "
        "(moments + scalars; ~1/n of the replicated footprint)")


def _m_reshard_hist():
    from ..telemetry import core as telemetry
    return telemetry.histogram(
        "hvd_zero_reshard_seconds",
        "Deterministic optimizer-state reshard on elastic world-size "
        "change")


# ==========================================================================
# Shard plan
# ==========================================================================

class BucketShard:
    """Shard geometry of one fusion bucket: ``size`` payload elements,
    padded to ``padded`` (a multiple of the granule = n × block so every
    rank owns a whole number of quantization blocks), ``shard_len`` =
    padded / n elements per rank."""

    __slots__ = ("size", "padded", "shard_len")

    def __init__(self, size, padded, shard_len):
        self.size = size
        self.padded = padded
        self.shard_len = shard_len

    def __repr__(self):
        return (f"BucketShard(size={self.size}, padded={self.padded}, "
                f"shard_len={self.shard_len})")


class ZeroPlan:
    """Deterministic pad-and-split shard plan (portable-collectives
    formulation): identical on every rank that agrees on the leaf
    shapes, world size, bucket budget, and quantization granule."""

    __slots__ = ("n", "bucket_bytes", "block", "buckets", "shards",
                 "leaf_shapes", "leaf_dtypes")

    def __init__(self, n, bucket_bytes, block, buckets, shards,
                 leaf_shapes, leaf_dtypes):
        self.n = n
        self.bucket_bytes = bucket_bytes
        self.block = block
        self.buckets = buckets
        self.shards = shards
        self.leaf_shapes = leaf_shapes
        self.leaf_dtypes = leaf_dtypes

    def signature(self):
        """JSON-able identity of the plan — what every rank must agree
        on (guardian digests carry it per collective leg)."""
        return {
            "n": self.n,
            "bucket_bytes": int(self.bucket_bytes),
            "block": int(self.block),
            "buckets": [
                {"indices": list(b.indices), "dtype": str(b.dtype),
                 "padded": s.padded, "shard_len": s.shard_len}
                for b, s in zip(self.buckets, self.shards)],
        }


def plan_zero(leaves, n, bucket_bytes=DEFAULT_ZERO_BUCKET_BYTES, block=1):
    """Build the shard plan: fusion buckets from
    :func:`bucketing.plan_buckets` (reversed leaf order — overlap
    priority preserved), each padded to a multiple of ``n × block`` and
    split into ``n`` equal shards. Uneven leaf sizes are absorbed by the
    per-bucket pad; tensors are never split across buckets."""
    from ..compression.codecs import padded_len
    n = int(n)
    if n < 1:
        raise ValueError(f"world size must be >= 1, got {n}")
    block = max(int(block), 1)
    buckets = plan_buckets(leaves, bucket_bytes)
    shards = []
    for b in buckets:
        size = sum(int(np.prod(leaves[i].shape)) for i in b.indices)
        # padded_len is the compression plane's every-rank-owns-whole-
        # blocks rule — one granule computation across both planes.
        padded = padded_len(size, n, block)
        shards.append(BucketShard(size, padded, padded // n))
    return ZeroPlan(n, bucket_bytes, block, buckets, shards,
                    [tuple(leaf.shape) for leaf in leaves],
                    [str(jnp.asarray(leaf).dtype)
                     if not hasattr(leaf, "dtype") else str(leaf.dtype)
                     for leaf in leaves])


# ==========================================================================
# Sharded state
# ==========================================================================
#
# ZeroState is a plain 3-tuple pytree:
#   (bucket_states, scatter_res, gather_res)
# - bucket_states: tuple of per-bucket inner optax states whose vector
#   leaves are the local (shard_len,) slice — sharded P(axis) so the
#   global leaf is the (padded,) flat vector, NEVER materialized
#   replicated (state is born sharded in init_state's shard_map body).
# - scatter_res: per-bucket (1, n, shard_len) f32 error-feedback
#   residual of the quantized reduce-scatter leg (this rank's encode
#   error over its full bucket) — () when no wire codec / EF off.
# - gather_res: per-bucket (shard_len,) f32 residual of the quantized
#   allgather leg — () likewise.


def _validate_elementwise_state(inner, shard_len, dtype):
    """Every >=1-D state leaf must mirror the flat parameter shard: an
    optax transform carrying a non-per-parameter vector (a schedule
    table, a per-layer mask) would be silently sharded along the
    replica axis and corrupt its layout."""
    shape = jax.eval_shape(
        inner.init, jax.ShapeDtypeStruct((shard_len,), dtype))
    for path, leaf in jax.tree_util.tree_flatten_with_path(shape)[0]:
        if leaf.ndim >= 1 and leaf.shape != (shard_len,):
            raise ValueError(
                "ZeRO-1 requires elementwise optimizer state; leaf "
                + jax.tree_util.keystr(path)
                + f" has shape {leaf.shape} != ({shard_len},) (the "
                "per-replica parameter shard). Use make_train_step "
                "without HVDTPU_ZERO for transforms with "
                "non-per-parameter state (per-layer masks, global-norm "
                "state, schedule tables).")
    return shape


def _state_spec_for(inner, shard_len, dtype, axis_name):
    from jax.sharding import PartitionSpec as P
    shape = jax.eval_shape(
        inner.init, jax.ShapeDtypeStruct((shard_len,), dtype))
    return jax.tree.map(
        lambda s: P(axis_name) if s.ndim >= 1 else P(), shape)


def _pack_padded(leaves, bucket, padded):
    buf = _pack(leaves, bucket)
    if buf.shape[0] != padded:
        buf = jnp.pad(buf, (0, padded - buf.shape[0]))
    return buf


# ==========================================================================
# Quantized legs (EQuARX formulation, per bucket)
# ==========================================================================

def _wire_reduce_scatter(rows, axis_name, codec, block, n, residual):
    """Quantized reduce-scatter leg: encode this rank's (n, shard_len)
    rows, all_to_all so rank r holds every rank's quantized row r,
    accumulate dequantized in f32. Returns (f32 shard SUM, new
    residual rows) — residual is the local encode error (None when EF
    is off)."""
    if residual is not None:
        rows = rows + residual
    q, s = codec.encode(rows, block)
    new_res = rows - codec.decode(q, s, block) if residual is not None \
        else None
    q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                       tiled=True)
    s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0,
                       tiled=True)
    shard = jnp.sum(codec.decode(q, s, block), axis=0)
    return shard, new_res


def _wire_all_gather(u, axis_name, codec, block, residual):
    """Quantized allgather leg: requantize the updated shard, gather
    every rank's payload + scales, dequantize. All ranks (including the
    owner) apply the DEQUANTIZED update so params stay replica-
    identical. Returns (f32 full buffer, new residual)."""
    if residual is not None:
        u = u + residual
    q, s = codec.encode(u, block)
    new_res = u - codec.decode(q, s, block) if residual is not None \
        else None
    qg = lax.all_gather(q, axis_name, tiled=True)
    sg = lax.all_gather(s, axis_name, tiled=True)
    return codec.decode(qg, sg, block), new_res


# ==========================================================================
# Runtime: one bound instance of (inner optimizer × plan × mesh × codec)
# ==========================================================================

class ZeroRuntime:
    """Everything the sharded update path needs, bound once: the inner
    optax transformation, the mesh/axis, the shard plan (built lazily
    from the first params tree), and the codec configuration. Owned by
    ``DistributedOptimizer`` when ``zero`` is on."""

    def __init__(self, inner, mesh, axis_name, op=reduce_ops.Average,
                 bucket_bytes=DEFAULT_ZERO_BUCKET_BYTES, codec=None,
                 block=0, error_feedback=None, prescale=None,
                 postscale=None):
        from ..compression import codecs as _codecs
        if op not in (reduce_ops.Average, reduce_ops.Sum):
            raise ValueError(
                "ZeRO-1 supports Average/Sum gradient reductions only "
                f"(got {reduce_ops.op_name(op)}: Adasum's per-tensor "
                "scale-invariant combination does not reduce-scatter)")
        self.inner = inner
        self.mesh = mesh
        self.axis_name = axis_name
        self.op = op
        self.n = int(mesh.shape[axis_name])
        self.bucket_bytes = int(bucket_bytes)
        self.codec = (_codecs.get_codec(codec) if isinstance(codec, str)
                      else codec)
        self.block = (int(block) or _codecs.DEFAULT_BLOCK) \
            if self.codec is not None and self.codec.wire else 0
        if error_feedback is None:
            error_feedback = envparse.get_bool(
                envparse.COMPRESSION_ERROR_FEEDBACK, True)
        self.error_feedback = bool(error_feedback) \
            and self.codec is not None and self.codec.wire
        self.prescale = prescale
        self.postscale = postscale
        self.plan = None
        self.treedef = None
        #: elastic membership version this runtime's plan belongs to —
        #: a bump means the shard geometry is stale and the state must
        #: reshard (reshard_state) before the next step.
        self.version = envparse.get_str(envparse.ELASTIC_VERSION, "0")
        self._log = get_logger()

    def stale_version(self):
        return (envparse.get_str(envparse.ELASTIC_VERSION, "0")
                != self.version)

    # -- plan --------------------------------------------------------------
    def ensure_plan(self, params):
        leaves, treedef = jax.tree.flatten(params)
        if self.plan is None:
            self.plan = plan_zero(
                leaves, self.n, self.bucket_bytes,
                block=self.block if self.block else 1)
            self.treedef = treedef
            for b, s in zip(self.plan.buckets, self.plan.shards):
                _validate_elementwise_state(
                    self.inner, s.shard_len, b.dtype)
        elif [tuple(leaf.shape) for leaf in leaves] \
                != self.plan.leaf_shapes:
            raise ValueError(
                "ZeRO-1 shard plan was built for a different parameter "
                "tree (leaf shapes changed); build a fresh "
                "DistributedOptimizer for the new model")
        return self.plan

    # -- specs -------------------------------------------------------------
    def state_specs(self):
        """PartitionSpec pytree mirroring the ZeroState structure (for
        shard_map in/out specs)."""
        from jax.sharding import PartitionSpec as P
        plan = self.plan
        bucket_specs = tuple(
            _state_spec_for(self.inner, s.shard_len, b.dtype,
                            self.axis_name)
            for b, s in zip(plan.buckets, plan.shards))
        if self.error_feedback:
            res_scatter = tuple(P(self.axis_name) for _ in plan.buckets)
            res_gather = tuple(P(self.axis_name) for _ in plan.buckets)
        else:
            res_scatter = res_gather = ()
        return (bucket_specs, res_scatter, res_gather)

    # -- init --------------------------------------------------------------
    def init_state(self, params):
        """Materialize the optimizer state SHARDED from step 0 — the
        shard_map body inits each bucket's inner state from the local
        parameter shard, so the replicated footprint never exists."""
        from jax.sharding import PartitionSpec as P
        plan = self.ensure_plan(params)
        self.verify_plan_consistency()
        n, axis = self.n, self.axis_name

        def body(p):
            leaves = jax.tree.leaves(p)
            states, res_s, res_g = [], [], []
            for b, s in zip(plan.buckets, plan.shards):
                buf = _pack_padded(leaves, b, s.padded)
                p_shard = buf.reshape(n, s.shard_len)[
                    lax.axis_index(axis)]
                states.append(self.inner.init(p_shard))
                if self.error_feedback:
                    res_s.append(jnp.zeros((1, n, s.shard_len),
                                           jnp.float32))
                    res_g.append(jnp.zeros((s.shard_len,), jnp.float32))
            return tuple(states), tuple(res_s), tuple(res_g)

        state = jax.jit(_shard_map(
            body, mesh=self.mesh, in_specs=(P(),),
            out_specs=self.state_specs(), check_vma=False))(params)
        _m_state_bytes().set(self.state_bytes(state))
        return state

    def state_bytes(self, state):
        """Per-replica optimizer-state bytes (moments sharded 1/n +
        replicated scalars; EF residuals excluded — they are
        compression state, accounted in docs/compression.md)."""
        total = 0
        for leaf in jax.tree.leaves(state[0]):
            nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            total += nbytes // self.n if np.ndim(leaf) >= 1 else nbytes
        return total

    # -- guardian ----------------------------------------------------------
    def leg_digests(self, rank):
        """Guardian digests for the plan's two collective legs. Every
        rank must derive the identical geometry (same padded sizes,
        same shard shapes) and its own shard index; a divergent rank —
        e.g. a different HVDTPU_ZERO_BUCKET_BYTES — would reduce
        mismatched buffers and corrupt params silently."""
        plan = self.plan
        sig = plan.signature()
        codec = None
        if self.codec is not None:
            codec = (f"{self.codec.name}@b{self.block}"
                     if self.block else self.codec.name)
        common = {
            "op": reduce_ops.op_name(self.op),
            "dtype": ",".join(str(b.dtype) for b in plan.buckets),
            "shapes": [[b["padded"]] for b in sig["buckets"]],
            "process_set": 0,
            "prescale": None if self.prescale is None
            else float(self.prescale),
            "postscale": None if self.postscale is None
            else float(self.postscale),
            "root_rank": None,
            "codec": codec,
            "shard_index": rank,
            "shard_shape": [[b["shard_len"]] for b in sig["buckets"]],
        }
        return {
            "zero_reduce_scatter": dict(common, kind="zero_reduce_scatter"),
            "zero_allgather": dict(common, kind="zero_allgather"),
        }

    def verify_plan_consistency(self, board=None, rank=None, size=None,
                                timeout_s=None):
        """Cross-rank plan check through the guardian board (multi-
        process cohorts with HVDTPU_CONSISTENCY_CHECK on): publish this
        rank's leg digests, compare every peer's. Raises
        CollectiveMismatchError naming the divergent rank + field."""
        from .. import guardian
        if board is None:
            if not envparse.get_int(envparse.CONSISTENCY_CHECK, 0):
                return
            from .. import basics
            rt = basics.runtime()
            if rt.topology.size <= 1:
                return
            board = guardian.make_cross_process_board()
            if board is None:
                return
            rank, size = rt.topology.rank, rt.topology.size
        mine = self.leg_digests(rank)
        for leg, digest in mine.items():
            board.put(f"zero.plan.{leg}.{rank}",
                      guardian.render_digest(digest))
        import json
        import time
        if timeout_s is None:
            timeout_s = envparse.get_float(
                envparse.CONSISTENCY_TIMEOUT, 10.0)
        for leg, digest in mine.items():
            deadline = time.monotonic() + timeout_s
            theirs_by_rank = {}
            waiting = set(range(size)) - {rank}
            while waiting:
                for r in sorted(waiting):
                    raw = board.get(f"zero.plan.{leg}.{r}")
                    if raw is not None:
                        theirs_by_rank[r] = json.loads(raw)
                        waiting.discard(r)
                if not waiting or time.monotonic() > deadline:
                    break
                time.sleep(0.01)
            if waiting:
                self._log.warning(
                    "zero: plan consistency check for %s skipped "
                    "rank(s) %s (no digest within %.1fs)", leg,
                    sorted(waiting), timeout_s)
            divergences = guardian.compare_digests(digest, theirs_by_rank)
            if divergences:
                from ..exceptions import CollectiveMismatchError
                lines = [f"  rank {r}: {field} = {theirs!r} (rank "
                         f"{rank} derived {ours!r})"
                         for r, field, theirs, ours in divergences]
                fields = sorted({d[1] for d in divergences})
                raise CollectiveMismatchError(
                    f"ZeRO-1 {leg} shard plan diverges across ranks "
                    f"(fields: {', '.join(fields)}):\n"
                    + "\n".join(lines) +
                    "\nEvery rank must derive the identical pad-and-"
                    "split plan — check HVDTPU_ZERO_BUCKET_BYTES / "
                    "HVDTPU_COMPRESSION agree on all ranks.",
                    divergences=divergences)

    # -- the sharded update ------------------------------------------------
    def _bucket_grad_shard(self, g_leaves, k, b, s, res_s, new_res_s):
        """Reduce-scatter leg of bucket ``k``: this rank's reduced
        gradient shard (prescale/op/postscale applied), wire-quantized
        when a wire codec is configured (EF residual threaded)."""
        n, axis = self.n, self.axis_name
        average = self.op == reduce_ops.Average
        g = _pack_padded(g_leaves, b, s.padded)
        if self.prescale is not None:
            g = g * jnp.asarray(self.prescale).astype(g.dtype)
        if self.codec is not None and self.codec.wire:
            rows = g.reshape(n, s.shard_len).astype(jnp.float32)
            res = res_s[k][0] if self.error_feedback else None
            g_shard, new_res = _wire_reduce_scatter(
                rows, axis, self.codec, self.block, n, res)
            if average:
                g_shard = g_shard / n
            g_shard = g_shard.astype(b.dtype)
            if self.error_feedback:
                new_res_s.append(new_res[None])
        elif self.codec is not None:
            # Cast codec: the narrow dtype rides the collective itself
            # (reference compression semantics).
            payload, _ = self.codec.encode(g, 0)
            g_shard = self.codec.decode(
                lax.psum_scatter(payload, axis, tiled=True),
                None, 0, dtype=b.dtype)
            if average:
                g_shard = g_shard / n
        else:
            g_shard = lax.psum_scatter(g, axis, tiled=True)
            if average:
                g_shard = g_shard / n
        if self.postscale is not None:
            g_shard = g_shard * jnp.asarray(
                self.postscale).astype(g_shard.dtype)
        return g_shard

    def _run(self, grads, state, params, gather_params):
        """Shared per-bucket loop (reversed-leaf order = backprop
        availability order, so XLA can overlap bucket k's collectives
        with remaining work): reduce-scatter the gradient bucket, step
        the inner optimizer over the local 1/n shard, allgather back.

        ``gather_params=True`` (the train-step path) applies the update
        to the parameter shard BEFORE the gather and transports the NEW
        params — the optimizer multiply and the parameter add stay
        adjacent, so XLA contracts them into the same fused (FMA) form
        the replicated update compiles to and the result is
        bit-identical; gathering raw updates and adding outside would
        put a collective between mul and add and lose the contraction
        (~1-ulp noise). ``gather_params=False`` (the optax ``update``
        contract) transports the updates instead.

        With a wire codec the gather leg always carries the quantized
        UPDATES (small, lr-scaled — far friendlier to block quantization
        than raw parameter values), and every rank — owner included —
        applies the dequantized payload, so params stay replica-
        identical."""
        plan = self.ensure_plan(params)
        n, axis = self.n, self.axis_name
        bucket_states, res_s, res_g = state
        g_leaves = jax.tree.leaves(grads)
        p_leaves = jax.tree.leaves(params)
        out = [None] * len(g_leaves)
        new_states, new_res_s, new_res_g = [], [], []
        for k, (b, s) in enumerate(zip(plan.buckets, plan.shards)):
            g_shard = self._bucket_grad_shard(
                g_leaves, k, b, s, res_s, new_res_s)
            # -- sharded optimizer step (1/n of the state) -----------------
            p = _pack_padded(p_leaves, b, s.padded)
            p_shard = p.reshape(n, s.shard_len)[lax.axis_index(axis)]
            u_shard, new_state_k = self.inner.update(
                g_shard, bucket_states[k], p_shard)
            new_states.append(new_state_k)
            # -- allgather leg ---------------------------------------------
            if self.codec is not None and self.codec.wire:
                res = res_g[k] if self.error_feedback else None
                u_full, new_res = _wire_all_gather(
                    u_shard.astype(jnp.float32), axis, self.codec,
                    self.block, res)
                u_full = u_full.astype(b.dtype)
                if self.error_feedback:
                    new_res_g.append(new_res)
                full = (p + u_full) if gather_params else u_full
            elif self.codec is not None:
                payload, _ = self.codec.encode(u_shard, 0)
                u_full = self.codec.decode(
                    lax.all_gather(payload, axis, tiled=True),
                    None, 0, dtype=b.dtype)
                full = (p + u_full) if gather_params else u_full
            elif gather_params:
                new_p_shard = p_shard + u_shard.astype(p_shard.dtype)
                full = lax.all_gather(new_p_shard, axis, tiled=True)
            else:
                full = lax.all_gather(u_shard, axis, tiled=True)
            if s.padded != s.size:
                full = lax.slice(full, (0,), (s.size,))
            _unpack(full, g_leaves, b, out)
        tree = jax.tree.unflatten(jax.tree.structure(grads), out)
        new_state = (tuple(new_states),
                     tuple(new_res_s) if self.error_feedback else (),
                     tuple(new_res_g) if self.error_feedback else ())
        return tree, new_state

    def apply_in_axis(self, grads, state, params):
        """Train-step path: returns ``(new_params, new_state)`` with
        the update applied inside the shard (bit-identical to the
        replicated update for plain fp32 Sum/Average — see _run)."""
        return self._run(grads, state, params, gather_params=True)

    def update_in_axis(self, grads, state, params):
        """optax ``update`` contract: returns ``(updates, new_state)``
        with the gathered update deltas. Prefer make_train_step (which
        uses apply_in_axis); applying these updates externally rounds
        once more than the replicated fused multiply-add (~1 ulp)."""
        return self._run(grads, state, params, gather_params=False)


# ==========================================================================
# Elastic reshard
# ==========================================================================

def unshard_moments(state, runtime):
    """Host-side view of the sharded moments: for every vector position
    of the inner state tree, the per-parameter-leaf moment arrays
    (padding stripped), plus the replicated scalar leaves. The building
    block of :func:`reshard_state` and of tests that compare sharded
    moments against a replicated oracle."""
    plan = runtime.plan
    bucket_states = state[0]
    treedefs = [jax.tree.structure(bs) for bs in bucket_states]
    if any(td != treedefs[0] for td in treedefs[1:]):
        raise ValueError("per-bucket inner states diverge in structure")
    nleaves = len(plan.leaf_shapes)
    nslots = len(jax.tree.leaves(bucket_states[0]))
    per_leaf = [[None] * nleaves for _ in range(nslots)]
    scalars = [None] * nslots
    for b, s, bs in zip(plan.buckets, plan.shards, bucket_states):
        flat = jax.tree.leaves(bs)
        for j, leaf in enumerate(flat):
            if np.ndim(leaf) == 0:
                scalars[j] = np.asarray(leaf)
                continue
            if not getattr(leaf, "is_fully_addressable", True):
                # Multi-process global mesh: this process cannot read
                # the peers' shards, so an in-place reshard is
                # impossible — the exit-restart elastic path (restore
                # from checkpoint at the new world size) is the
                # supported route there.
                raise RuntimeError(
                    "zero: cannot reshard optimizer state in place — a "
                    "state shard lives on non-addressable devices "
                    "(multi-process global mesh). Restore from a "
                    "checkpoint after the elastic restart instead "
                    "(docs/performance.md \"ZeRO-1\").")
            vec = np.asarray(jax.device_get(leaf))[:s.size]
            offset = 0
            for i in b.indices:
                size = int(np.prod(plan.leaf_shapes[i]))
                per_leaf[j][i] = vec[offset:offset + size]
                offset += size
    return per_leaf, scalars, treedefs[0]


def _shard_reader(bucket_states, old_runtime, slot):
    """Windowed ``read_window`` over the old cohort's sharded moment
    vectors for one inner-state slot: resolves (rank, bucket) to the
    rank's addressable device shard and slices the requested window —
    at most one shard is ever resident host-side (cached between
    consecutive windows), so the fully-replicated flat vector the old
    gather-everything path materialized never exists."""
    devices = list(old_runtime.mesh.devices.flat)
    dev_rank = {id(d): r for r, d in enumerate(devices)}
    shard_by = {}  # (bucket k) -> {rank: jax shard}
    for k, bs in enumerate(bucket_states):
        leaf = jax.tree.leaves(bs)[slot]
        shard_by[k] = {dev_rank[id(sh.device)]: sh
                       for sh in leaf.addressable_shards
                       if id(sh.device) in dev_rank}
    cache = {}

    def read_window(rank, buf, start, length):
        _, k = buf
        key = (k, rank)
        if key not in cache:
            cache.clear()
            cache[key] = np.asarray(
                shard_by[k][rank].data).reshape(-1)
        return cache[key][start:start + length]

    return read_window


def reshard_state(state, old_runtime, new_runtime, params):
    """Deterministic optimizer-state redistribution for an elastic
    world-size change, emitted by the redistribution planner
    (``horovod_tpu/resharding/``): the old and new ``ZeroPlan``\\ s
    become flat-shard :class:`~horovod_tpu.resharding.Spec`\\ s, the
    planner derives the bounded-window program (cheapest legal
    candidate under the α–β cost model, guardian-verified and proven
    HVD501/HVD502-clean), and the host executor assembles each NEW
    rank's shard from windowed reads of the OLD ranks' addressable
    shards — peak host memory stays within one shard + 2×
    ``HVDTPU_RESHARD_BUCKET_BYTES`` instead of the full flat vector.
    Error-feedback residuals are ZEROED — the old cohort's
    quantization debt does not line up with the new shard geometry
    (same contract as the eager ResidualStore's version-keyed reset).
    Observed into ``hvd_zero_reshard_seconds``."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from .. import resharding
    from ..telemetry import span as tele_span
    with tele_span(["zero"], "ZERO_RESHARD",
                   histogram=_m_reshard_hist()):
        new_plan = new_runtime.ensure_plan(params)
        old_plan = old_runtime.plan
        bucket_states = state[0]
        treedefs = [jax.tree.structure(bs) for bs in bucket_states]
        if any(td != treedefs[0] for td in treedefs[1:]):
            raise ValueError(
                "per-bucket inner states diverge in structure")
        treedef = treedefs[0]
        for leaf in jax.tree.leaves(bucket_states):
            if np.ndim(leaf) >= 1 \
                    and not getattr(leaf, "is_fully_addressable", True):
                # Multi-process global mesh: this process cannot read
                # the peers' shards, so an in-place reshard is
                # impossible — the exit-restart elastic path (restore
                # from checkpoint at the new world size) is the
                # supported route there.
                raise RuntimeError(
                    "zero: cannot reshard optimizer state in place — "
                    "a state shard lives on non-addressable devices "
                    "(multi-process global mesh). Restore from a "
                    "checkpoint after the elastic restart instead "
                    "(docs/performance.md \"ZeRO-1\").")
        meta = list(zip(old_plan.leaf_shapes, old_plan.leaf_dtypes))
        src_spec = resharding.zero_flat_spec(
            old_plan, axis=old_runtime.axis_name)
        dst_spec = resharding.zero_flat_spec(
            new_plan, axis=new_runtime.axis_name)
        program = resharding.plan_redistribution(src_spec, dst_spec,
                                                 meta)
        program.verify_consistency()
        axis = new_runtime.axis_name
        mesh = new_runtime.mesh
        new_devices = list(mesh.devices.flat)
        rep_sharding = NamedSharding(mesh, P())
        slot0 = jax.tree.leaves(bucket_states[0])
        nslots = len(slot0)
        # per bucket: the flat list of new inner-state leaves
        new_flat = [[None] * nslots
                    for _ in range(len(new_plan.buckets))]
        for j in range(nslots):
            if np.ndim(slot0[j]) == 0:
                scalar = np.asarray(slot0[j])
                for k in range(len(new_plan.buckets)):
                    new_flat[k][j] = jax.device_put(scalar,
                                                    rep_sharding)
                continue
            dtypes = {str(jax.tree.leaves(bs)[j].dtype)
                      for bs in bucket_states}
            override = dtypes.pop() if len(dtypes) == 1 else None
            results, _ = resharding.execute_host(
                program, _shard_reader(bucket_states, old_runtime, j),
                dtype_override=override)
            for k, s in enumerate(new_plan.shards):
                vec_sharding = NamedSharding(mesh, P(axis))
                new_flat[k][j] = \
                    jax.make_array_from_single_device_arrays(
                        (s.padded,), vec_sharding,
                        [jax.device_put(results[r][("bucket", k)], d)
                         for r, d in enumerate(new_devices)])
        new_bucket_states = [jax.tree.unflatten(treedef, flat)
                             for flat in new_flat]
        if new_runtime.error_feedback:
            n = new_runtime.n
            res_s = tuple(
                jax.device_put(
                    np.zeros((n, n, s.shard_len), np.float32),
                    vec_sharding)
                for s in new_plan.shards)
            res_g = tuple(
                jax.device_put(np.zeros((s.padded,), np.float32),
                               vec_sharding)
                for s in new_plan.shards)
        else:
            res_s = res_g = ()
        new_state = (tuple(new_bucket_states), res_s, res_g)
        _m_state_bytes().set(new_runtime.state_bytes(new_state))
        get_logger().warning(
            "zero: optimizer state resharded %d-way -> %d-way "
            "(%d bucket(s); error-feedback residuals reset — "
            "quantization debt never crosses cohorts)",
            old_runtime.n, new_runtime.n, len(new_plan.buckets))
        return new_state
