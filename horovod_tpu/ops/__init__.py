from .reduce_ops import Sum, Average, Adasum, Min, Max, Product  # noqa: F401
