"""Elastic Keras state + callbacks under ``horovod_tpu.keras``
(reference: horovod/keras/elastic.py:22 KerasState, :34-76 elastic
callbacks).
"""

from ..elastic import run  # noqa: F401
from ..tensorflow.elastic import TensorFlowKerasState


class KerasState(TensorFlowKerasState):
    """State of a Keras model and optimizer for elastic training
    (reference: horovod/keras/elastic.py:22)."""


_LAZY = ("CommitStateCallback", "UpdateBatchStateCallback",
         "UpdateEpochStateCallback")


def __getattr__(name):
    """Lazy class creation, cached in module globals so repeated access
    returns the SAME class (isinstance/identity checks must hold). The
    name check comes FIRST so attribute probes for other names raise
    AttributeError without importing keras."""
    if name not in _LAZY:
        raise AttributeError(name)
    from .._keras.elastic import make_elastic_callbacks
    globals().update(zip(_LAZY, make_elastic_callbacks()))
    return globals()[name]
