"""Keras binding (reference: horovod/keras/__init__.py:201 +
horovod/tensorflow/keras/__init__.py). Works with Keras 3's multi-backend
model.fit: gradients sync across hvdrun-launched ranks inside
``optimizer.apply`` regardless of the compute backend (tensorflow eager/
graph, torch, jax-eager).

The TPU path — model math compiled on the chips — is the jax backend plus
:func:`set_data_parallel`: model.fit's jitted train step then runs as ONE
XLA program over the device mesh, batch sharded, variables replicated,
gradient reduction lowered natively by XLA (the TPU-native redesign of the
reference's XLA custom-call bridge, reference:
horovod/tensorflow/xla_mpi_ops.cc:174-232).

    import horovod_tpu.keras as hvd
    hvd.init()
    hvd.set_data_parallel()          # KERAS_BACKEND=jax: train on-chip
    opt = hvd.DistributedOptimizer(keras.optimizers.SGD(0.01 * hvd.size()))
    model.compile(optimizer=opt, ...)
    model.fit(..., callbacks=[
        hvd.callbacks.BroadcastGlobalVariablesCallback(0)])
"""

from .. import basics
from ..ops import reduce_ops
from ..ops.compression import Compression
from .._keras import (create_distributed_optimizer, rank, size,
                      spmd_active)

Average = reduce_ops.Average
Sum = reduce_ops.Sum
Adasum = reduce_ops.Adasum

from . import elastic  # noqa: E402,F401  (hvd.elastic.KerasState)

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
local_rank = basics.local_rank
local_size = basics.local_size
cross_rank = basics.cross_rank
cross_size = basics.cross_size
metrics_snapshot = basics.metrics_snapshot

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "DistributedOptimizer", "broadcast_global_variables",
           "allreduce", "allgather", "broadcast", "load_model",
           "set_data_parallel", "callbacks"]


def set_data_parallel(devices=None, auto_shard_dataset=True):
    """Compile keras model.fit onto the device mesh (jax backend only).

    Activates ``keras.distribution.DataParallel`` over the runtime's
    devices: every batch is sharded along its leading axis, variables are
    replicated, and the jitted train step compiles to one XLA program in
    which the gradient reduction is a native ICI collective — no host
    round-trip (contrast reference: horovod/tensorflow/xla_mpi_ops.cc:
    174-232, which bridges collectives out of XLA through custom calls).

    In single-controller mode the mesh is the runtime's local device list;
    in multi-process SPMD mode (jax.distributed global mesh) it spans every
    process's devices and keras shards per-process data into the global
    array. Call after ``hvd.init()`` and BEFORE building the model (layout
    is assigned when variables are created).
    """
    import keras
    if keras.backend.backend() != "jax":
        raise RuntimeError(
            "set_data_parallel requires the jax keras backend "
            f"(KERAS_BACKEND=jax); current backend is "
            f"{keras.backend.backend()!r}. On other backends use "
            "DistributedOptimizer's per-process sync under hvdrun.")
    rt = basics.runtime()
    if rt.mode == basics.MODE_SPMD and rt.topology.size > 1 and \
            not getattr(rt.backend, "global_mesh_spmd", False):
        raise RuntimeError(
            "set_data_parallel in multi-process mode requires the "
            "jax.distributed global mesh (HVDTPU_CPU_OPERATIONS=xla): "
            "over the host (TCP) plane each process only sees its local "
            "devices, so a DataParallel there would train each rank "
            "alone. Use run_eagerly=True for per-process sync instead.")
    if devices is None:
        if rt.mode == basics.MODE_SPMD:
            import jax
            devices = list(jax.devices())
        else:
            devices = list(rt.devices)
    dist = keras.distribution.DataParallel(
        devices=devices, auto_shard_dataset=auto_shard_dataset)
    keras.distribution.set_distribution(dist)
    return dist


def DistributedOptimizer(optimizer, name=None, device_dense="",
                         device_sparse="", compression=None,
                         sparse_as_dense=False, gradient_predivide_factor=1.0,
                         op=Average, backward_passes_per_step=1,
                         average_aggregated_gradients=True):
    """Reference: horovod/keras/__init__.py:36 DistributedOptimizer.
    ``compression`` (Compression.fp16/bf16) applies on the host/eager
    sync planes; ``device_dense``/``device_sparse``/``sparse_as_dense``
    are GPU placement/densification knobs the TPU design absorbs (grads
    on the sync plane are always dense)."""
    import keras
    return create_distributed_optimizer(
        keras, optimizer, name=name, op=op,
        gradient_predivide_factor=gradient_predivide_factor,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        compression=compression)


def broadcast_global_variables(root_rank=0, model=None):
    """Broadcast a model's weights from root_rank (reference:
    horovod/keras/__init__.py broadcast_global_variables).

    Keras 3 has no global-variables registry, so the model must be
    passed explicitly; a silent no-op here would let ranks keep
    divergent initial weights (the reference likewise fails loud in
    eager mode rather than guess)."""
    if model is None:
        raise ValueError(
            "broadcast_global_variables needs the model: pass "
            "model=<keras model>, use callbacks."
            "BroadcastGlobalVariablesCallback(root_rank) in model.fit, or "
            "broadcast the arrays directly with "
            "horovod_tpu.functions.broadcast_variables.")
    if not spmd_active():
        return
    import numpy as np
    from ..functions import broadcast_variables as _bv
    synced = _bv(model.get_weights(), root_rank=root_rank)
    model.set_weights([np.asarray(w) for w in synced])


def allreduce(value, name=None, average=True,
              prescale_factor=1.0, postscale_factor=1.0, op=None,
              compression=None):
    import numpy as np
    import keras
    from ..ops import collectives as _c
    if op is None:
        op = Average if average else Sum
    if not spmd_active():
        return value
    out = _c.allreduce(np.asarray(keras.ops.convert_to_numpy(value)),
                       op=op, name=name,
                       compression=compression or Compression.none,
                       prescale_factor=prescale_factor,
                       postscale_factor=postscale_factor)
    return keras.ops.convert_to_tensor(np.asarray(out))


def allgather(value, name=None):
    import numpy as np
    import keras
    from ..ops import collectives as _c
    if not spmd_active():
        return value
    out = _c.allgather(np.asarray(keras.ops.convert_to_numpy(value)),
                       name=name)
    return keras.ops.convert_to_tensor(np.asarray(out))


def broadcast(value, root_rank, name=None):
    import numpy as np
    import keras
    from ..ops import collectives as _c
    if not spmd_active():
        return value
    out = _c.broadcast(np.asarray(keras.ops.convert_to_numpy(value)),
                       root_rank, name=name)
    return keras.ops.convert_to_tensor(np.asarray(out))


def load_model(filepath, *, custom_optimizers=None, custom_objects=None,
               compression=None, compile=True, **kwargs):  # noqa: A002
    """Load a model and wrap its optimizer (reference:
    horovod/keras/__init__.py:167 load_model — same kwarg surface:
    ``custom_optimizers`` extends the deserializable classes,
    ``compression`` is applied to the re-wrapped optimizer so a model
    trained with wire compression keeps it after reload).

    The extra parameters are keyword-only: positionally they would
    shadow ``keras.models.load_model(filepath, custom_objects)`` and
    silently bind a custom_objects dict to custom_optimizers."""
    import keras
    if custom_optimizers:
        custom_objects = dict(custom_objects or {})
        custom_objects.update({cls.__name__: cls
                               for cls in custom_optimizers})
    model = keras.models.load_model(filepath,
                                    custom_objects=custom_objects,
                                    compile=compile, **kwargs)
    if compile and getattr(model, "optimizer", None) is not None:
        model.optimizer = DistributedOptimizer(model.optimizer,
                                               compression=compression)
    return model


class _Callbacks:
    """Lazy namespace: hvd.callbacks.BroadcastGlobalVariablesCallback etc.
    (reference: horovod/_keras/callbacks.py). Created classes are cached
    on the instance so repeated access returns the SAME class
    (isinstance/identity checks must hold)."""

    def __getattr__(self, item):
        from .._keras.callbacks import make_callbacks
        from .._keras.elastic import make_elastic_callbacks
        (bgv, ma, warmup, sched) = make_callbacks()
        (commit, upd_batch, upd_epoch) = make_elastic_callbacks()
        mapping = {
            "BroadcastGlobalVariablesCallback": bgv,
            "MetricAverageCallback": ma,
            "LearningRateWarmupCallback": warmup,
            "LearningRateScheduleCallback": sched,
            "CommitStateCallback": commit,
            "UpdateBatchStateCallback": upd_batch,
            "UpdateEpochStateCallback": upd_epoch,
        }
        if item not in mapping:
            raise AttributeError(item)
        self.__dict__.update(mapping)
        return mapping[item]


callbacks = _Callbacks()
