"""Disk checkpointing helpers (TPU value-add).

The reference has no checkpoint engine of its own — elastic State objects
are in-memory and disk persistence is left to user code / Keras callbacks
(SURVEY §5.4). On TPU the idiomatic store is orbax; these helpers add the
distributed etiquette around it: rank-0-only writes, a barrier so no rank
races ahead of an in-flight save, and restore-then-broadcast so every
rank starts from identical bytes.

    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt

    ckpt.save(path, {"params": params, "opt": opt_state, "epoch": 3})
    state = ckpt.restore(path)               # broadcast from rank 0
    state = ckpt.restore_latest(directory)   # newest step under directory
"""

import os

from . import basics
from .functions import broadcast_object
from .ops.collectives import barrier


def _spmd():
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


def _rank():
    return basics.runtime().topology.rank


def save(path, state):
    """Write ``state`` (a pytree) at ``path``; rank 0 writes, everyone
    waits at a barrier so no rank resumes training against a half-written
    checkpoint."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(str(path))
    if not _spmd() or _rank() == 0:
        with ocp.PyTreeCheckpointer() as ckptr:
            ckptr.save(path, state, force=True)
    if _spmd():
        barrier()


def restore(path, target=None):
    """Load a checkpoint. In SPMD mode rank 0 reads the bytes and
    broadcasts — one storage read per job, identical state everywhere
    (the elastic sync-from-survivor pattern applied to disk)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(str(path))
    state = None
    if not _spmd() or _rank() == 0:
        with ocp.PyTreeCheckpointer() as ckptr:
            state = ckptr.restore(path, item=target)
    if _spmd():
        state = broadcast_object(state, root_rank=0, name="ckpt.restore")
    return state


def save_step(directory, step, state):
    """Save under ``directory/step_<N>`` (monotonic step layout)."""
    save(os.path.join(str(directory), f"step_{step}"), state)


def latest_step(directory):
    """Highest step with a checkpoint under ``directory``, or None."""
    directory = str(directory)
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                continue
    return max(steps) if steps else None


def restore_latest(directory, target=None):
    """Restore the newest ``step_<N>`` checkpoint; returns (step, state)
    or (None, None) when the directory holds none."""
    step = latest_step(directory)
    if _spmd():
        # All ranks must agree on which step to load (a rank may race a
        # concurrent save when listing).
        step = broadcast_object(step, root_rank=0, name="ckpt.latest")
    if step is None:
        return None, None
    return step, restore(os.path.join(str(directory), f"step_{step}"),
                         target=target)
