"""Crash-safe disk checkpointing (TPU value-add).

The reference has no checkpoint engine of its own — elastic State objects
are in-memory and disk persistence is left to user code / Keras callbacks
(SURVEY §5.4). These helpers add the distributed etiquette (rank-0-only
writes, a barrier so no rank races ahead of an in-flight save,
restore-then-broadcast so every rank starts from identical bytes) AND the
durability etiquette a preemptible fleet needs:

- **Atomic writes**: every save lands as tmp-file → flush → fsync →
  rename (+ directory fsync), so a crash mid-save leaves either the old
  checkpoint or the new one — never a half-written file at the final
  name.
- **Integrity footer**: each file carries a SHA-256 checksum of its
  payload plus framing magic; ``restore`` verifies before unpickling and
  raises ``CheckpointCorruptError`` on damage instead of handing back
  garbage.
- **Fallback restore**: ``restore_latest`` walks steps newest-first and
  restores the newest *intact* one, warning about (and counting,
  ``hvd_checkpoint_corrupt_total``) every corrupt file it skips.
- **Retention**: ``HVDTPU_CHECKPOINT_KEEP=N`` prunes all but the newest
  N steps after each ``save_step``.

Legacy orbax checkpoints (directories) remain restorable; new saves use
the single-file format. ``checkpoint`` is a chaos injection point
(``checkpoint:corrupt`` flips payload bytes after the file lands) so the
fallback path is rehearsable on demand (docs/fault_tolerance.md).

    import horovod_tpu as hvd
    from horovod_tpu import checkpoint as ckpt

    ckpt.save(path, {"params": params, "opt": opt_state, "epoch": 3})
    state = ckpt.restore(path)               # broadcast from rank 0
    state = ckpt.restore_latest(directory)   # newest INTACT step
"""

import hashlib
import os
import pickle
import struct

from . import basics
from . import chaos
from .exceptions import CheckpointCorruptError
from .functions import broadcast_object
from .ops.collectives import barrier
from .telemetry import core as telemetry
from .utils import envparse
from .utils.logging_util import get_logger

MAGIC = b"HVDTPUCKPT1\n"
_FOOTER = struct.Struct("<32sQ")  # sha256(payload), payload length
_MIN_SIZE = len(MAGIC) * 2 + _FOOTER.size


def _m_corrupt():
    # Resolved at call time (corruption is a rare event): NULL no-op
    # when HOROVOD_TPU_METRICS is off.
    return telemetry.counter(
        "hvd_checkpoint_corrupt_total",
        "Checkpoint files that failed their integrity check")


def _spmd():
    if not basics.is_initialized():
        # Checkpoint helpers stay usable before init() (inspection
        # tools, tests): no runtime means no peers to coordinate with.
        return False
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


def _rank():
    return basics.runtime().topology.rank


def _to_host(state):
    """Device arrays → host numpy so the pickled payload is stable and
    device-independent (restore hands back numpy leaves)."""
    import jax
    import numpy as np

    def conv(x):
        return np.asarray(x) if isinstance(x, jax.Array) else x

    return jax.tree_util.tree_map(conv, state)


def _write_file(path, state):
    """Atomic single-file write: MAGIC | payload | sha256 | len | MAGIC,
    via tmp + fsync + rename so a crash never leaves a torn file at the
    final name."""
    payload = pickle.dumps(_to_host(state),
                           protocol=pickle.HIGHEST_PROTOCOL)
    footer = _FOOTER.pack(hashlib.sha256(payload).digest(), len(payload))
    directory = os.path.dirname(path) or "."
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(MAGIC)
            f.write(payload)
            f.write(footer)
            f.write(MAGIC)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        # Persist the rename itself (directory entry durability).
        dir_fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
    except OSError:
        pass  # e.g. directories that reject O_RDONLY fsync (some FSes)
    try:
        chaos.inject("checkpoint", name=os.path.basename(path))
    except chaos.ChaosSignal as sig:
        if sig.action == "corrupt":
            _chaos_corrupt(path, len(payload))


def _chaos_corrupt(path, payload_len):
    """Chaos ``checkpoint:corrupt``: flip bytes in the middle of the
    just-written payload (length preserved) so the checksum fails."""
    with open(path, "r+b") as f:
        f.seek(len(MAGIC) + max(0, payload_len // 2 - 8))
        chunk = f.read(16)
        f.seek(-len(chunk), os.SEEK_CUR)
        f.write(bytes(b ^ 0xFF for b in chunk))
    get_logger().warning("chaos: corrupted checkpoint payload in %s",
                         path)


def verify_checkpoint(path):
    """Integrity check without unpickling. Returns ``(ok, reason)``;
    legacy orbax directories report ok (orbax owns their layout)."""
    if os.path.isdir(path):
        return True, "legacy orbax directory"
    try:
        with open(path, "rb") as f:
            # One fd for stat + reads: immune to a concurrent atomic
            # save replacing the path mid-check.
            size = os.fstat(f.fileno()).st_size
            if size < _MIN_SIZE:
                return False, f"truncated ({size} bytes)"
            head = f.read(len(MAGIC))
            if head != MAGIC:
                return False, "bad header magic (foreign or torn file)"
            f.seek(size - len(MAGIC))
            if f.read(len(MAGIC)) != MAGIC:
                return False, "bad trailer magic (truncated write)"
            f.seek(size - len(MAGIC) - _FOOTER.size)
            digest, payload_len = _FOOTER.unpack(f.read(_FOOTER.size))
            if len(MAGIC) + payload_len + _FOOTER.size + len(MAGIC) \
                    != size:
                return False, (f"length mismatch (footer says "
                               f"{payload_len} payload bytes)")
            f.seek(len(MAGIC))
            h = hashlib.sha256()
            left = payload_len
            while left > 0:
                chunk = f.read(min(left, 1 << 20))
                if not chunk:
                    return False, "payload shorter than footer claims"
                h.update(chunk)
                left -= len(chunk)
            if h.digest() != digest:
                return False, "checksum mismatch (payload corrupted)"
    except OSError as exc:
        return False, f"unreadable: {exc}"
    return True, ""


def _read_file(path):
    ok, reason = verify_checkpoint(path)
    if not ok:
        _m_corrupt().inc()
        raise CheckpointCorruptError(
            f"checkpoint {path} failed its integrity check: {reason}")
    with open(path, "rb") as f:
        # fstat on the OPEN fd: a concurrent atomic save may os.replace
        # the path between open and a path-based stat, and the old fd's
        # bytes must pair with the old fd's size.
        size = os.fstat(f.fileno()).st_size
        payload_len = size - _MIN_SIZE
        f.seek(len(MAGIC))
        return pickle.loads(f.read(payload_len))


def _read_any(path, target):
    if os.path.isdir(path):
        # Legacy orbax layout from before the single-file format.
        import orbax.checkpoint as ocp
        with ocp.PyTreeCheckpointer() as ckptr:
            return ckptr.restore(path, item=target)
    return _read_file(path)


def save(path, state):
    """Write ``state`` (a pytree) at ``path``; rank 0 writes atomically
    (tmp + fsync + rename + checksum footer), everyone waits at a
    barrier so no rank resumes training against an in-flight save."""
    path = os.path.abspath(str(path))
    if not _spmd() or _rank() == 0:
        _write_file(path, state)
    if _spmd():
        barrier()


def restore(path, target=None):
    """Load and verify a checkpoint. In SPMD mode rank 0 reads the bytes
    and broadcasts — one storage read per job, identical state
    everywhere. Raises ``CheckpointCorruptError`` when the file fails
    its integrity check (use ``restore_latest`` for automatic fallback
    to an older intact step)."""
    path = os.path.abspath(str(path))
    state = None
    err = None
    if not _spmd() or _rank() == 0:
        try:
            state = _read_any(path, target)
        except (CheckpointCorruptError, OSError) as exc:
            if not _spmd():
                raise
            # Rank 0 raising BEFORE the broadcast would strand every
            # other rank inside broadcast_object forever: ship the
            # failure through the broadcast and raise on all ranks.
            err = f"{type(exc).__name__}: {exc}"
    if _spmd():
        err, state = broadcast_object((err, state), root_rank=0,
                                      name="ckpt.restore")
        if err is not None:
            raise CheckpointCorruptError(
                f"rank 0 could not restore {path}: {err}")
    return state


def save_step(directory, step, state):
    """Save under ``directory/step_<N>`` (monotonic step layout), then
    prune to the newest ``HVDTPU_CHECKPOINT_KEEP`` steps (0 = keep
    everything)."""
    directory = str(directory)
    if not _spmd() or _rank() == 0:
        os.makedirs(directory, exist_ok=True)
    save(os.path.join(directory, f"step_{step}"), state)
    if not _spmd() or _rank() == 0:
        _apply_retention(directory)


def _apply_retention(directory):
    keep = envparse.get_int(envparse.CHECKPOINT_KEEP, 0)
    if keep <= 0:
        return
    import shutil
    for step in sorted(_list_steps(directory), reverse=True)[keep:]:
        path = os.path.join(directory, f"step_{step}")
        try:
            if os.path.isdir(path):
                shutil.rmtree(path)
            else:
                os.unlink(path)
        except OSError as exc:
            get_logger().warning("checkpoint retention: could not "
                                 "remove %s: %s", path, exc)


def _list_steps(directory):
    """Step numbers under ``directory``. Non-checkpoint entries a real
    directory accumulates — editor temp files, ``.tmp.<pid>`` partials
    from a crashed writer — are skipped with a warning instead of
    crashing the listing."""
    steps, skipped = [], []
    for name in os.listdir(directory):
        if not name.startswith("step_"):
            continue
        try:
            steps.append(int(name[5:]))
        except ValueError:
            skipped.append(name)
    if skipped:
        shown = ", ".join(sorted(skipped)[:5])
        more = "" if len(skipped) <= 5 else f" (+{len(skipped) - 5} more)"
        get_logger().warning(
            "checkpoint: ignoring %d non-checkpoint entr%s in %s: %s%s",
            len(skipped), "y" if len(skipped) == 1 else "ies",
            directory, shown, more)
    return steps


def latest_step(directory):
    """Highest step with a checkpoint under ``directory``, or None."""
    directory = str(directory)
    if not os.path.isdir(directory):
        return None
    steps = _list_steps(directory)
    return max(steps) if steps else None


def _latest_intact_step(directory):
    """Newest step whose file passes verification; corrupt files are
    skipped (warned + counted) in favor of older intact ones. Raises
    ``CheckpointCorruptError`` when steps exist but NONE are intact —
    silently training from scratch over a damaged store would be worse
    than stopping."""
    if not os.path.isdir(directory):
        return None
    steps = sorted(_list_steps(directory), reverse=True)
    if not steps:
        return None
    log = get_logger()
    for step in steps:
        path = os.path.join(directory, f"step_{step}")
        ok, reason = verify_checkpoint(path)
        if ok:
            if step != steps[0]:
                log.warning(
                    "checkpoint: falling back to step %d (newest intact "
                    "checkpoint under %s)", step, directory)
            return step
        _m_corrupt().inc()
        log.warning("checkpoint: step %d is corrupt (%s); trying the "
                    "previous step", step, reason)
    raise CheckpointCorruptError(
        f"all {len(steps)} checkpoint(s) under {directory} failed their "
        f"integrity checks (steps {steps}); refusing to silently train "
        "from scratch")


def restore_latest(directory, target=None):
    """Restore the newest *intact* ``step_<N>`` checkpoint; returns
    ``(step, state)`` or ``(None, None)`` when the directory holds none.
    Corrupt newer steps are skipped with a warning (and counted in
    ``hvd_checkpoint_corrupt_total``) in favor of older intact ones."""
    directory = str(directory)
    step = None
    err = None
    if not _spmd() or _rank() == 0:
        try:
            step = _latest_intact_step(directory)
        except (CheckpointCorruptError, OSError) as exc:
            if not _spmd():
                raise
            # Same stranding hazard as restore(): the error must travel
            # through the broadcast, not pre-empt it on rank 0 only.
            err = f"{type(exc).__name__}: {exc}"
    if _spmd():
        # All ranks must agree on which step to load (a rank may race a
        # concurrent save when listing).
        err, step = broadcast_object((err, step), root_rank=0,
                                     name="ckpt.latest")
        if err is not None:
            raise CheckpointCorruptError(
                f"rank 0 could not pick a checkpoint under {directory}: "
                f"{err}")
    if step is None:
        return None, None
    return step, restore(os.path.join(directory, f"step_{step}"),
                         target=target)
