"""tf.function → JAX compiler: run TensorFlow-2 model math on the TPU.

The reference runs TF model math on the accelerator by registering its
collective kernels for device execution (reference:
horovod/tensorflow/mpi_ops.cc:486-493) and can compile collectives into
XLA programs through paired custom calls (reference:
horovod/tensorflow/xla_mpi_ops.cc:174-232). This image's TF is CPU-only,
so a kernel-registration port would leave the model on the host. The
TPU-first answer mirrors the torch binding's round-3 design
(horovod_tpu/torch/compile.py): treat the TF program as the model
*definition* — trace it once with ``tf.function``, walk the
ConcreteFunction graph, and rebuild it as a pure JAX function over a flat
variable dict. The chip then runs XLA end-to-end: jit, shard_map
collectives, optax, the Pallas kernels.

    compiled = tpu_compile(loss_fn, example_inputs=(x, y))
    loss = compiled(x, y)                                # jitted forward
    step = compiled.make_train_step(optax.adam(1e-3))    # fwd+bwd+update
    loss = step((x, y))                                  # on the chip
    compiled.copy_params_to_variables()                  # sync back to TF

Supported surface: the forward op set of TF2 models (conv/pool/matmul/
batch-norm/embedding/activations/reductions/shape ops, the softmax cross
entropies, stateless function calls). Gradients never need translating —
JAX differentiates the rebuilt function. Unsupported ops raise with the
node name so coverage gaps are explicit, not silent. Variable writes
(``AssignAddVariableOp`` — e.g. batch-norm moving stats) are captured
functionally and applied to the compiled module's buffers after each
train step.

Caveats: runs under JAX x64-off — int64 becomes int32, float64 becomes
float32. Shapes are static (trace with concrete example inputs).
Data-dependent TF control flow (``tf.while_loop``/``tf.cond`` on traced
values) is out of scope — the same restriction XLA itself imposes on TPU.
"""

import math

import numpy as np


def _jnp():
    import jax.numpy as jnp
    return jnp


def _jdt(tf_dtype):
    """tf dtype -> jax dtype under x64-off semantics."""
    import jax.numpy as jnp
    name = tf_dtype.name if hasattr(tf_dtype, "name") else str(tf_dtype)
    table = {
        "float64": jnp.float32, "float32": jnp.float32,
        "float16": jnp.float16, "bfloat16": jnp.bfloat16,
        "int64": jnp.int32, "int32": jnp.int32, "int16": jnp.int16,
        "int8": jnp.int8, "uint8": jnp.uint8, "uint16": jnp.uint16,
        "uint32": jnp.uint32, "bool": jnp.bool_,
        "complex64": jnp.complex64,
    }
    if name not in table:
        raise NotImplementedError(f"tf dtype {name} has no jax mapping")
    return table[name]


def _np_narrow(arr):
    """Narrow 64-bit numpy arrays the way JAX x64-off would."""
    arr = np.asarray(arr)
    if arr.dtype == np.float64:
        return arr.astype(np.float32)
    if arr.dtype == np.int64:
        return arr.astype(np.int32)
    if arr.dtype == np.uint64:
        return arr.astype(np.uint32)
    return arr


class _Var:
    """Resource-handle token flowing through the interpreted graph."""

    __slots__ = ("name",)

    def __init__(self, name):
        self.name = name


def _is_static(x):
    return isinstance(x, (int, float, bool, np.ndarray, np.generic,
                          list, tuple))


def _static_ints(x, what):
    """Shape-like operand -> python int list (must be trace-static)."""
    if hasattr(x, "aval"):  # jax tracer
        raise NotImplementedError(
            f"{what} must be trace-static (shapes are static under XLA); "
            "got a traced value")
    return [int(v) for v in np.asarray(x).reshape(-1)]


def _axis_list(x, what):
    return _static_ints(x, what)


def _pool(x, ksize, strides, padding, kind):
    import jax.lax as lax
    jnp = _jnp()
    if isinstance(padding, bytes):
        padding = padding.decode()
    window = tuple(int(k) for k in ksize)
    strides = tuple(int(s) for s in strides)
    if kind == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) \
            else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, padding)
    summed = lax.reduce_window(x.astype(jnp.float32), 0.0, lax.add,
                               window, strides, padding)
    if padding == "VALID":
        count = float(np.prod(window))
        return (summed / count).astype(x.dtype)
    ones = jnp.ones(x.shape, jnp.float32)
    count = lax.reduce_window(ones, 0.0, lax.add, window, strides, padding)
    return (summed / count).astype(x.dtype)


def _strided_slice(x, begin, end, strides, begin_mask, end_mask,
                   ellipsis_mask, new_axis_mask, shrink_axis_mask):
    """Full tf.strided_slice semantics over a jax array or numpy value."""
    begin = _static_ints(begin, "StridedSlice begin")
    end = _static_ints(end, "StridedSlice end")
    strides = _static_ints(strides, "StridedSlice strides")
    spec = []
    n_spec = len(begin)
    # Expand ellipsis into full-dim slices.
    n_new = bin(new_axis_mask).count("1")
    for i in range(n_spec):
        if ellipsis_mask & (1 << i):
            n_explicit = n_spec - 1 - n_new
            for _ in range(np.ndim(x) - n_explicit
                           if hasattr(x, "ndim") else 0):
                spec.append(slice(None))
        elif new_axis_mask & (1 << i):
            spec.append(None)
        elif shrink_axis_mask & (1 << i):
            spec.append(begin[i])
        else:
            b = None if begin_mask & (1 << i) else begin[i]
            e = None if end_mask & (1 << i) else end[i]
            s = strides[i]
            spec.append(slice(b, e, s))
    return x[tuple(spec)]


def _sparse_softmax_ce(logits, labels):
    import jax
    jnp = _jnp()
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                               axis=-1)[..., 0]
    grad = jax.nn.softmax(lf, axis=-1) - jax.nn.one_hot(
        labels, logits.shape[-1], dtype=jnp.float32)
    return nll, grad


def _softmax_ce(logits, labels):
    import jax
    jnp = _jnp()
    lf = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(lf, axis=-1)
    loss = -jnp.sum(labels.astype(jnp.float32) * logp, axis=-1)
    grad = jax.nn.softmax(lf, axis=-1) - labels.astype(jnp.float32)
    return loss, grad


def _conv2d(x, w, strides, padding, dilations, data_format,
            explicit_paddings=()):
    import jax.lax as lax
    if isinstance(data_format, bytes):
        data_format = data_format.decode()
    if data_format != "NHWC":
        raise NotImplementedError(
            f"Conv2D data_format {data_format}: the TPU path is NHWC")
    if isinstance(padding, bytes):
        padding = padding.decode()
    if padding == "EXPLICIT":
        pads = list(explicit_paddings)
        padding = [(pads[2], pads[3]), (pads[4], pads[5])]
    # Under compute_dtype the weights carry the chosen precision; graph
    # constants (e.g. keras Rescaling) can drift activations back to
    # fp32 — follow the weight (lax.conv requires matching dtypes).
    if x.dtype != w.dtype:
        x = x.astype(w.dtype)
    # Grouped convolution: TF keeps the op type Conv2D and encodes the
    # group count implicitly as in_channels / rhs_in_channels (e.g.
    # ConvNeXt's 7x7 depthwise is Conv2D with groups == channels).
    groups, rem = divmod(x.shape[-1], w.shape[2])
    if rem:
        raise NotImplementedError(
            f"Conv2D input channels {x.shape[-1]} not divisible by "
            f"kernel input channels {w.shape[2]}")
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:3]), padding=padding,
        rhs_dilation=tuple(dilations[1:3]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=groups)


def _depthwise_conv2d(x, w, strides, padding, dilations, data_format):
    import jax.lax as lax
    if isinstance(data_format, bytes):
        data_format = data_format.decode()
    if data_format != "NHWC":
        raise NotImplementedError("DepthwiseConv2d: NHWC only")
    if isinstance(padding, bytes):
        padding = padding.decode()
    if x.dtype != w.dtype:
        x = x.astype(w.dtype)  # see _conv2d: weights carry compute_dtype
    h, kw, cin, mult = w.shape
    w = w.reshape(h, kw, 1, cin * mult)
    return lax.conv_general_dilated(
        x, w, window_strides=tuple(strides[1:3]), padding=padding,
        rhs_dilation=tuple(dilations[1:3]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin)


def _fused_batch_norm(interp, op, x, scale, offset, mean, var):
    jnp = _jnp()
    eps = op.get_attr("epsilon")
    training = op.get_attr("is_training")
    fmt = op.get_attr("data_format")
    fmt = fmt.decode() if isinstance(fmt, bytes) else fmt
    if fmt != "NHWC":
        raise NotImplementedError("FusedBatchNorm: NHWC only")
    xf = x.astype(jnp.float32)
    if training:
        bmean = jnp.mean(xf, axis=(0, 1, 2))
        bvar = jnp.var(xf, axis=(0, 1, 2))
    else:
        bmean, bvar = mean.astype(jnp.float32), var.astype(jnp.float32)
    inv = 1.0 / jnp.sqrt(bvar + eps)
    y = ((xf - bmean) * inv * scale.astype(jnp.float32)
         + offset.astype(jnp.float32)).astype(x.dtype)
    n = x.shape[0] * x.shape[1] * x.shape[2]
    # TF's "reserve" outputs feed the fused backward kernel; JAX
    # differentiates the forward math instead, so any tensor works —
    # batch stats keep shapes consistent. Unbiased variance matches the
    # moving-variance update TF emits.
    uvar = bvar * (n / max(n - 1, 1)) if training else bvar
    return (y, bmean, uvar, bmean, bvar, jnp.zeros_like(bvar))


def _einsum_handler(op, args):
    eq = op.get_attr("equation")
    eq = eq.decode() if isinstance(eq, bytes) else eq
    return _jnp().einsum(eq, *args)


# ---------------------------------------------------------------------------
# Flash-attention routing (Einsum → [scale] → [mask add] → Softmax → Einsum)
# ---------------------------------------------------------------------------

def _note_flash_fallback(reason):
    from ..ops.flash_attention import note_flash_fallback
    note_flash_fallback(reason)


def _einsum_labels(op):
    """Parse a 2-operand, rank-4, no-ellipsis einsum equation into
    (lhs0, lhs1, out) label strings; None when it does not qualify."""
    eq = op.get_attr("equation")
    eq = eq.decode() if isinstance(eq, bytes) else eq
    if "..." in eq or "->" not in eq:
        return None
    lhs, out = eq.split("->")
    parts = lhs.split(",")
    if len(parts) != 2:
        return None
    a, b = parts
    if not (len(a) == len(b) == len(out) == 4):
        return None
    if len(set(a)) != 4 or len(set(b)) != 4 or len(set(out)) != 4:
        return None
    return a, b, out


def _match_attention(sm):
    """Recognize the keras/HF attention triple around a Softmax op:

        scores = einsum(E1, X0, X1)       # QKᵀ in any label layout
        scores = scores * c | scores / c  # optional scalar scale
        scores = scores + mask            # optional additive mask
        probs  = softmax(scores)          # last axis
        out    = einsum(E2, probs, V)     # in either operand order

    Identification is semantic (einsum-label bookkeeping), not equation
    string matching, so any batch/head/seq layout qualifies. Returns a
    list of (combine_op_name, plan). The plan stores tensor NAMES; the
    interpreter resolves them against the live env at dispatch time so
    scale/mask constancy is judged on actual traced values. Chain
    intermediates may have extra consumers (e.g. a Shape feeding
    ones_like, or returned attention scores): they still execute
    normally — only the combine einsum's output is substituted, so
    every other consumer keeps its exact value.

    reference: no counterpart — the reference framework has no attention
    compute at all; this serves BASELINE's "model math on the
    accelerator at native efficiency" bar for bridged keras models."""
    chain = sm.inputs[0].op
    scale_name = None
    scale_kind = None
    mask_name = None
    mask_kind = None
    neg_name = None
    for _ in range(3):
        if chain.type in ("Mul", "RealDiv"):
            if scale_name:
                return None
            i0, i1 = chain.inputs
            if chain.type == "RealDiv":
                # chain must be the numerator
                if i1.shape.rank == 0:
                    scale_name, scale_kind, chain = i1.name, "div", i0.op
                    continue
                return None
            if i1.shape.rank == 0:
                scale_name, scale_kind, chain = i1.name, "mul", i0.op
                continue
            if i0.shape.rank == 0:
                scale_name, scale_kind, chain = i0.name, "mul", i1.op
                continue
            return None
        if chain.type in ("Add", "AddV2"):
            if mask_name:
                return None
            i0, i1 = chain.inputs
            # the scores operand is the one produced by the rest of the
            # chain (einsum / scale); the other is the additive mask
            if i0.op.type in ("Einsum", "Mul", "RealDiv"):
                mask_name, mask_kind, chain = i1.name, "add", i0.op
                continue
            if i1.op.type in ("Einsum", "Mul", "RealDiv"):
                mask_name, mask_kind, chain = i0.name, "add", i1.op
                continue
            return None
        if chain.type == "SelectV2":
            # keras masked softmax: where(keep_mask, scores, big_negative)
            if mask_name:
                return None
            cond, on_true, on_false = chain.inputs
            mask_name, mask_kind = cond.name, "select"
            neg_name = on_false.name
            chain = on_true.op
            continue
        break
    if chain.type != "Einsum":
        return None
    e1 = chain
    labels = _einsum_labels(e1)
    if labels is None:
        return None
    a_l, b_l, s_l = labels
    contracted = (set(a_l) & set(b_l)) - set(s_l)
    if len(contracted) != 1:
        return None
    h = contracted.pop()
    sk = s_l[-1]                      # softmax axis label (last)
    in_a, in_b = sk in a_l, sk in b_l
    if in_a == in_b:
        return None
    k_l, k_t = (a_l, e1.inputs[0]) if in_a else (b_l, e1.inputs[1])
    q_l, q_t = (b_l, e1.inputs[1]) if in_a else (a_l, e1.inputs[0])
    shared_bh = [l for l in s_l if l in q_l and l in k_l]
    if len(shared_bh) != 2:
        return None
    bb, hh = shared_bh
    sq_set = set(q_l) - {bb, hh, h}
    if len(sq_set) != 1:
        return None
    sq = sq_set.pop()
    if set(s_l) != {bb, hh, sq, sk} or set(k_l) != {bb, hh, sk, h}:
        return None

    matches = []
    for e2 in sm.outputs[0].consumers():
        if e2.type != "Einsum":
            continue
        labels2 = _einsum_labels(e2)
        if labels2 is None:
            continue
        l20, l21, o_l = labels2
        if e2.inputs[0].op is sm:
            p_l, v_l, v_t = l20, l21, e2.inputs[1]
        elif e2.inputs[1].op is sm:
            p_l, v_l, v_t = l21, l20, e2.inputs[0]
        else:
            continue
        # Translate E2's labels into E1's label space positionally via
        # the probs operand (its axes ARE E1's output axes).
        trans = {p_l[i]: s_l[i] for i in range(4)}
        c2 = (set(p_l) & set(v_l)) - set(o_l)
        if len(c2) != 1 or trans[next(iter(c2))] != sk:
            continue
        hv = [l for l in v_l if l not in trans]
        if len(hv) != 1:
            continue
        tv = [trans.get(l, "HV") for l in v_l]
        if set(tv) != {bb, hh, sk, "HV"}:
            continue
        to = [trans.get(l, "HV") for l in o_l]
        if set(to) != {bb, hh, sq, "HV"}:
            continue
        matches.append((e2.name, {
            "q": q_t.name, "k": k_t.name, "v": v_t.name,
            "perm_q": tuple(q_l.index(x) for x in (bb, hh, sq, h)),
            "perm_k": tuple(k_l.index(x) for x in (bb, hh, sk, h)),
            "perm_v": tuple(tv.index(x) for x in (bb, hh, sk, "HV")),
            "out_perm": tuple((bb, hh, sq, "HV").index(x) for x in to),
            "scale": scale_name, "scale_kind": scale_kind,
            "mask": mask_name, "mask_kind": mask_kind, "neg": neg_name,
        }))
    return matches


def _attention_plans(graph):
    plans = {}
    for op in graph.get_operations():
        if op.type != "Softmax":
            continue
        hit = _match_attention(op)
        if hit is None:
            continue
        for name, plan in hit:
            plans[name] = plan
    return plans


_VALUE_FREE_ROOTS = frozenset({"Shape", "ShapeN", "Size", "Rank", "Const"})
_TAINT_OPS = frozenset({
    "Placeholder", "Arg", "_Arg", "ReadVariableOp", "ResourceGather",
    "VarHandleOp", "AssignVariableOp", "AssignAddVariableOp",
    "AssignSubVariableOp", "PartitionedCall", "StatefulPartitionedCall",
    "StatelessRandomGetKeyCounter", "StatelessRandomGetAlg",
})


def _value_free_ops(graph):
    """Op names whose outputs depend on no graph input's runtime VALUES
    (only static shapes), no variable, and no RNG. JAX omnistaging
    stages every op inside a jit trace, so keras's shape-derived mask
    chains (ones_like → GreaterEqual → LogicalAnd) would reach the
    attention pattern as tracers; ops in this set run under
    ``jax.ensure_compile_time_eval()`` instead, keeping those masks
    concrete so _try_flash_attention can classify them statically."""
    free = set()
    for op in graph.get_operations():
        t = op.type
        if t in _VALUE_FREE_ROOTS:
            free.add(op.name)
            continue
        if t in _TAINT_OPS or t in _RANDOM_OPS or t == "NoOp":
            continue
        if all(i.op.name in free or i.op.type in _VALUE_FREE_ROOTS
               for i in op.inputs):
            free.add(op.name)
    return free


def _classify_static_mask(mval, kind, n_q, n_k):
    """For a concrete mask ('add': additive float, zeros keep / ≤-1e8
    block; 'select': boolean keep-mask): ('none', 0) if it keeps
    everything, ('causal', q_offset) if it is exactly a (broadcast)
    bottom-right-aligned causal pattern — keep[i, j] iff
    j <= i + (n_k - n_q), which the kernel reproduces with
    q_offset = n_k - n_q — else None (fall back to einsum)."""
    # mval is concrete (the caller filtered tracers) — concretize with
    # numpy directly: jnp.asarray would re-lift it into the ambient
    # trace (JVP/grad) where even ensure_compile_time_eval cannot
    # concretize it back on older jax.
    m = np.asarray(mval)
    if kind == "select":
        if m.dtype != np.bool_:
            return None
        keep = m
        blocked = ~m
    else:
        m = m.astype(np.float32)
        keep = m == 0
        blocked = m <= -1e8
    if not (keep | blocked).all():
        return None
    if keep.all():
        return "none", 0
    if keep.ndim < 2 or keep.shape[-2:] != (n_q, n_k):
        return None
    flat = keep.reshape(-1, n_q, n_k)
    if not (flat == flat[0]).all():
        return None
    causal = np.tril(np.ones((n_q, n_k), bool), k=n_k - n_q)
    if (flat[0] == causal).all():
        return "causal", n_k - n_q
    return None


def _concrete_or_none(x):
    from ..utils.jax_compat import concrete_or_none
    return concrete_or_none(x)


def _try_flash_attention(env, plan, opr):
    """Attempt to compute the recognized attention pattern with the
    Pallas flash kernel. Returns the combine-einsum's output or None
    (caller falls back to the plain einsum lowering)."""
    import jax
    jnp = _jnp()
    q, k, v = env.get(plan["q"]), env.get(plan["k"]), env.get(plan["v"])
    if q is None or k is None or v is None:
        return None
    if not all(getattr(x, "ndim", 0) == 4 for x in (q, k, v)):
        return None
    qt = jnp.transpose(q, plan["perm_q"])
    kt = jnp.transpose(k, plan["perm_k"])
    vt = jnp.transpose(v, plan["perm_v"])
    if not (qt.shape[-1] == kt.shape[-1] == vt.shape[-1]
            and qt.shape[-1] <= 128
            and qt.shape[:2] == kt.shape[:2] == vt.shape[:2]
            and kt.shape[2] == vt.shape[2]):
        _note_flash_fallback(
            f"unsupported attention shapes q{qt.shape} k{kt.shape} "
            f"v{vt.shape}")
        return None
    sm_scale = 1.0
    if plan["scale"] is not None:
        sval = _concrete_or_none(env.get(plan["scale"]))
        if sval is None:
            _note_flash_fallback("non-constant attention scale")
            return None
        # concrete (tracers filtered above): concretize via numpy —
        # jnp.asarray would re-lift into an ambient JVP/grad trace.
        sm_scale = float(np.asarray(sval))
        if plan["scale_kind"] == "div":
            if sm_scale == 0.0:
                return None
            sm_scale = 1.0 / sm_scale
    causal = False
    if plan["mask"] is not None:
        mval = _concrete_or_none(env.get(plan["mask"]))
        if mval is None:
            _note_flash_fallback(
                "attention mask is not a compile-time constant")
            return None
        if plan["mask_kind"] == "select":
            # the on-false fill must actually block (≤ -1e8)
            neg = _concrete_or_none(env.get(plan["neg"]))
            if neg is None:
                _note_flash_fallback("non-constant masked-softmax fill")
                return None
            neg_ok = bool((np.asarray(neg) <= -1e8).all())
            if not neg_ok:
                _note_flash_fallback(
                    "masked-softmax fill value is not a large negative")
                return None
        verdict = _classify_static_mask(mval, plan["mask_kind"],
                                        qt.shape[2], kt.shape[2])
        if verdict is None:
            _note_flash_fallback(
                "attention mask is neither all-keep nor causal")
            return None
        kind, q_offset = verdict
        causal = kind == "causal"
    else:
        q_offset = 0
    from ..ops.flash_attention import flash_attention
    out = flash_attention(qt, kt, vt, causal=causal, sm_scale=sm_scale,
                          q_offset=q_offset)
    return jnp.transpose(out, plan["out_perm"])


def _matmul(a, b, transpose_a=False, transpose_b=False, adjoint=False):
    """MatMul transpose_a/b is a plain transpose; BatchMatMul adj_x/y is
    the adjoint — conjugate-transpose for complex inputs."""
    jnp = _jnp()
    if transpose_a:
        if adjoint and jnp.iscomplexobj(a):
            a = a.conj()
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        if adjoint and jnp.iscomplexobj(b):
            b = b.conj()
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _bias_add(x, b, data_format=b"NHWC"):
    fmt = data_format.decode() if isinstance(data_format, bytes) \
        else data_format
    if fmt == "NCHW" and x.ndim == 4:
        return x + b.reshape(1, -1, 1, 1)
    return x + b


def _reduction(fn_name):
    def handler(interp, op, x, axes):
        keep = op.get_attr("keep_dims")
        # TF lowers axis=None to an explicit all-dims const; axis=[] (an
        # empty axes tensor) means "reduce nothing", which numpy/jnp
        # express the same way. Static operands stay numpy: under
        # omnistaging a jnp call would stage even a constant into the
        # trace, poisoning downstream shape math.
        ax = tuple(_axis_list(axes, f"{op.type} axes"))
        if isinstance(x, (np.ndarray, np.generic)):
            return np.asarray(getattr(np, fn_name)(x, axis=ax,
                                                   keepdims=keep))
        return getattr(_jnp(), fn_name)(x, axis=ax, keepdims=keep)
    return handler


def _concat(args, interp, op):
    *values, axis = args
    axis = int(np.asarray(axis))
    if all(isinstance(v, (np.ndarray, np.generic, int, float))
           for v in values):
        return np.concatenate([np.asarray(v) for v in values], axis=axis)
    return _jnp().concatenate(values, axis=axis)


def _pack(args, axis):
    if all(_is_static(a) for a in args):
        return np.stack([np.asarray(a) for a in args], axis=axis)
    return _jnp().stack(args, axis=axis)


def _hvd_query_op_value(opr):
    """Resolve one of this binding's rank/size py_function graph ops to
    its current value (see the EagerPyFunc dispatch case). Foreign
    py_functions are genuinely uncompilable host calls — fail loud."""
    import re
    from . import (rank, local_rank, size, local_size)
    leaf = opr.name.rsplit("/", 1)[-1]
    if "horovod_local_rank" in leaf:
        return np.int32(local_rank())
    if "horovod_local_size" in leaf:
        return np.int32(local_size())
    if "horovod_rank" in leaf:
        return np.int32(rank())
    m = re.search(r"horovod_process_set_included_ps(\d+)", leaf)
    if m:
        from ..process_sets import process_set_by_id
        ps = process_set_by_id(int(m.group(1)))
        if ps is None:
            raise ValueError(f"no process set with id {m.group(1)}")
        return np.int32(1 if ps.included() else 0)
    if "horovod_process_set_included" in leaf:
        raise NotImplementedError(
            f"EagerPyFunc {opr.name!r}: process_set_included_op over an "
            "unregistered process set (id None) cannot be resolved in a "
            "compiled program; add the process set before tracing")
    m = re.search(r"horovod_size_ps(\d+)", leaf)
    if m:
        from . import _process_set_size
        return np.int32(_process_set_size(int(m.group(1))))
    if "horovod_size" in leaf:
        return np.int32(size())
    raise NotImplementedError(
        f"EagerPyFunc {opr.name!r}: arbitrary py_function host calls "
        "cannot run inside a compiled TPU program. If this is one of the "
        "binding's rank/size ops created with a custom name=, keep the "
        "default name — the bridge resolves them by their name markers")


class _GraphInterpreter:
    """Execute a ConcreteFunction graph with jax values.

    Values are keyed by tensor name ("node:idx"). Resource handles flow as
    :class:`_Var` tokens; ``ReadVariableOp``/``ResourceGather`` resolve
    them against the params/buffers dicts, ``Assign*VariableOp`` records a
    functional update instead of mutating. Random ops draw from a fold_in
    of one PRNG key per site (deterministic given the key)."""

    def __init__(self, graph, capture_values, fdef_library):
        self.graph = graph
        self.capture_values = capture_values  # placeholder name -> value
        self.fdefs = fdef_library
        self.rng_sites = {}
        self._number_rng_sites(graph, prefix="")
        self._plan_cache = {}   # graph -> {einsum op name: flash plan}
        self._gctx = None       # (env, plans) of the graph being run

    def _number_rng_sites(self, graph, prefix):
        for opr in graph.get_operations():
            if opr.type in _RANDOM_OPS:
                self.rng_sites[prefix + opr.name] = len(self.rng_sites)

    def run(self, params, buffers, inputs, rng=None, training=False):
        """inputs: list matching graph.inputs' non-capture prefix.
        Returns (flat_outputs, buffer_updates)."""
        self.params = params
        self.buffers = buffers
        self.rng = rng
        self.training = training
        self.updates = {}
        env = {}
        n_args = len(inputs)
        for i, t in enumerate(self.graph.inputs):
            if i < n_args:
                env[t.name] = inputs[i]
            elif t.name in self.capture_values:
                env[t.name] = self.capture_values[t.name]
            else:
                raise KeyError(f"graph input {t.name} has no binding")
        out_env = self._run_graph(self.graph, env, prefix="")
        flat = [out_env[t.name] for t in self.graph.outputs]
        return flat, self.updates

    def _run_graph(self, graph, env, prefix):
        import jax
        if graph not in self._plan_cache:
            self._plan_cache[graph] = (_attention_plans(graph),
                                       _value_free_ops(graph))
        plans, value_free = self._plan_cache[graph]
        prev_ctx = self._gctx
        self._gctx = (env, plans)
        try:
            for opr in graph.get_operations():
                if opr.type in ("Placeholder", "Arg", "_Arg"):
                    continue  # bound by caller
                if opr.type == "NoOp":
                    continue
                args = [env[t.name] for t in opr.inputs]
                if opr.name in value_free:
                    # Shape-derived subgraph: evaluate eagerly so the
                    # result stays a compile-time constant under the jit
                    # trace (see _value_free_ops).
                    with jax.ensure_compile_time_eval():
                        outs = self._dispatch(opr, args, prefix)
                else:
                    outs = self._dispatch(opr, args, prefix)
                if outs is _SKIP:
                    continue
                if not isinstance(outs, tuple):
                    outs = (outs,)
                for t, v in zip(opr.outputs, outs):
                    env[t.name] = v
        finally:
            self._gctx = prev_ctx
        return env

    def _rng_key(self, opr, prefix):
        import jax
        if self.rng is None:
            raise ValueError(
                f"graph contains random op {opr.name} ({opr.type}); pass "
                "rng= (a jax PRNG key) to the compiled call")
        return jax.random.fold_in(self.rng,
                                  self.rng_sites[prefix + opr.name])

    def _resolve_var(self, token, what):
        if not isinstance(token, _Var):
            raise NotImplementedError(
                f"{what} on a non-variable resource")
        if token.name in self.params:
            return self.params[token.name]
        if token.name in self.buffers:
            # A buffer may have a pending in-graph update (e.g. BN moving
            # stats assigned then read); reads see the latest write, like
            # TF's resource ordering.
            return self.updates.get(token.name, self.buffers[token.name])
        raise KeyError(f"variable {token.name} not found")

    def _call_function(self, opr, args, prefix):
        attr = opr.node_def.attr["f"].func.name
        fdef = self.fdefs.get(attr)
        if fdef is None:
            raise NotImplementedError(
                f"function {attr!r} called by {opr.name} not in library")
        from tensorflow.python.framework import function_def_to_graph
        fg = function_def_to_graph.function_def_to_graph(fdef)
        sub_prefix = prefix + opr.name + "/"
        if sub_prefix not in getattr(self, "_numbered", set()):
            self._numbered = getattr(self, "_numbered", set())
            self._numbered.add(sub_prefix)
            self._number_rng_sites(fg, sub_prefix)
        env = {}
        for t, v in zip(fg.inputs, args):
            env[t.name] = v
        out_env = self._run_graph(fg, env, sub_prefix)
        return tuple(out_env[t.name] for t in fg.outputs)

    def _dispatch(self, opr, args, prefix):
        import jax
        jnp = _jnp()
        t = opr.type

        if t == "Const":
            import tensorflow as tf
            val = _np_narrow(tf.make_ndarray(opr.get_attr("value")))
            return val
        if t in ("Identity", "PreventGradient", "EnsureShape",
                 "CheckNumerics", "Snapshot"):
            return args[0]
        if t == "IdentityN":
            return tuple(args)
        if t == "StopGradient":
            import jax.lax as lax
            return lax.stop_gradient(args[0])
        if t == "ReadVariableOp":
            return self._resolve_var(args[0], "ReadVariableOp")
        if t == "ResourceGather":
            table = self._resolve_var(args[0], "ResourceGather")
            return jnp.take(table, args[1].astype(jnp.int32)
                            if hasattr(args[1], "astype") else args[1],
                            axis=0)
        if t in ("AssignVariableOp", "AssignAddVariableOp",
                 "AssignSubVariableOp"):
            token, value = args[0], args[1]
            if not isinstance(token, _Var):
                raise NotImplementedError(f"{t} on non-variable resource")
            if token.name in self.params:
                raise NotImplementedError(
                    f"{t} writes trainable variable {token.name} inside "
                    "the compiled function; train through "
                    "make_train_step instead")
            cur = self.updates.get(token.name,
                                   self.buffers.get(token.name))
            if t == "AssignVariableOp":
                self.updates[token.name] = value
            elif t == "AssignAddVariableOp":
                self.updates[token.name] = cur + value
            else:
                self.updates[token.name] = cur - value
            return _SKIP
        if t in ("PartitionedCall", "StatefulPartitionedCall"):
            return self._call_function(opr, args, prefix)

        if t == "EagerPyFunc":
            # The binding's rank/size graph ops are py_functions (they
            # resolve at execution time on the eager plane, surviving an
            # elastic shutdown();init()). Inside a compiled program a
            # host call is impossible, so resolve them to the CURRENT
            # runtime value at trace time — a fresh trace after a reset
            # observes the new topology. Identified by the op-name
            # markers the binding embeds (including the process-set id).
            return _hvd_query_op_value(opr)

        if t == "StatelessRandomGetKeyCounter":
            # TF's seed->key/counter derivation; our randomness comes from
            # the caller's jax PRNG key (fold_in per site), so these are
            # inert placeholders consumed by the StatelessRandom*V2 ops.
            return (np.zeros([1], np.uint32), np.zeros([2], np.uint32))
        if t == "StatelessRandomGetAlg":
            return np.int32(1)
        if t in _RANDOM_OPS:
            key = self._rng_key(opr, prefix)
            shape = tuple(_static_ints(args[0], f"{t} shape"))
            dt = _jdt(opr.get_attr("dtype"))
            if "Uniform" in t:
                return jax.random.uniform(key, shape, dtype=dt)
            return jax.random.normal(key, shape, dtype=dt)

        if t == "Shape":
            return np.asarray(np.shape(args[0]), np.int32)
        if t == "ShapeN":
            return tuple(np.asarray(np.shape(a), np.int32) for a in args)
        if t == "Size":
            return np.int32(np.prod(np.shape(args[0])))
        if t == "Rank":
            return np.int32(np.ndim(args[0]))
        if t == "Reshape":
            shape = _static_ints(args[1], "Reshape shape")
            x = args[0]
            return (np.reshape(x, shape) if isinstance(x, np.ndarray)
                    else x.reshape(shape))
        if t == "Squeeze":
            dims = [int(d) for d in opr.get_attr("squeeze_dims")]
            return jnp.squeeze(args[0], axis=tuple(dims) if dims else None)
        if t == "ExpandDims":
            ax = int(np.asarray(args[1]))
            x = args[0]
            return (np.expand_dims(x, ax) if isinstance(x, np.ndarray)
                    else jnp.expand_dims(x, ax))
        if t == "Transpose":
            perm = _static_ints(args[1], "Transpose perm")
            return jnp.transpose(args[0], perm)
        if t == "Pack":
            return _pack(args, int(opr.get_attr("axis")))
        if t == "Unpack":
            ax = int(opr.get_attr("axis"))
            n = int(opr.get_attr("num"))
            parts = jnp.split(args[0], n, axis=ax)
            return tuple(jnp.squeeze(p, axis=ax) for p in parts)
        if t == "ConcatV2":
            return _concat(args, self, opr)
        if t == "Split":
            ax = int(np.asarray(args[0]))
            n = int(opr.get_attr("num_split"))
            return tuple(jnp.split(args[1], n, axis=ax))
        if t == "SplitV":
            sizes = _static_ints(args[1], "SplitV sizes")
            ax = int(np.asarray(args[2]))
            idx = np.cumsum(sizes)[:-1]
            return tuple(jnp.split(args[0], idx, axis=ax))
        if t == "StridedSlice":
            return _strided_slice(
                args[0], args[1], args[2], args[3],
                opr.get_attr("begin_mask"), opr.get_attr("end_mask"),
                opr.get_attr("ellipsis_mask"),
                opr.get_attr("new_axis_mask"),
                opr.get_attr("shrink_axis_mask"))
        if t == "Slice":
            begin = _static_ints(args[1], "Slice begin")
            size = _static_ints(args[2], "Slice size")
            spec = tuple(slice(b, None if s == -1 else b + s)
                         for b, s in zip(begin, size))
            return args[0][spec]
        if t == "Tile":
            reps = _static_ints(args[1], "Tile multiples")
            return jnp.tile(args[0], reps)
        if t == "Fill":
            shape = tuple(_static_ints(args[0], "Fill dims"))
            return jnp.full(shape, args[1])
        if t == "ZerosLike":
            return jnp.zeros_like(args[0])
        if t == "OnesLike":
            return jnp.ones_like(args[0])
        if t == "Range":
            s, l, d = (np.asarray(a) for a in args[:3])
            if all(_is_static(a) for a in args[:3]):
                return np.arange(int(s), int(l), int(d),
                                 dtype=_jdt(opr.get_attr("Tidx")))
            return jnp.arange(args[0], args[1], args[2])
        if t == "BroadcastTo":
            shape = tuple(_static_ints(args[1], "BroadcastTo shape"))
            return jnp.broadcast_to(args[0], shape)
        if t == "GatherV2":
            ax = int(np.asarray(args[2]))
            batch_dims = int(opr.get_attr("batch_dims"))
            if batch_dims:
                # take_along_axis matches tf.gather batch semantics only
                # when indices rank == params rank; other batched shapes
                # would mis-broadcast silently.
                if np.ndim(args[1]) != np.ndim(args[0]):
                    raise NotImplementedError(
                        f"GatherV2 (node {opr.name}) with batch_dims="
                        f"{batch_dims} and indices rank "
                        f"{np.ndim(args[1])} != params rank "
                        f"{np.ndim(args[0])} has no jax mapping")
                return jnp.take_along_axis(args[0], args[1], axis=ax)
            return jnp.take(args[0], args[1], axis=ax)
        if t == "Pad":
            pads = [tuple(p) for p in
                    np.asarray(args[1], np.int64).tolist()]
            return jnp.pad(args[0], pads)
        if t == "PadV2":
            pads = [tuple(p) for p in
                    np.asarray(args[1], np.int64).tolist()]
            return jnp.pad(args[0], pads, constant_values=args[2])
        if t == "Cumsum":
            return jnp.cumsum(args[0], axis=int(np.asarray(args[1])))
        if t == "ReverseV2":
            axes = tuple(_axis_list(args[1], "ReverseV2 axis"))
            return jnp.flip(args[0], axis=axes)
        if t in ("ResizeNearestNeighbor", "ResizeBilinear"):
            size = _static_ints(args[1], f"{t} size")
            method = "nearest" if t == "ResizeNearestNeighbor" \
                else "bilinear"
            if opr.get_attr("align_corners") or \
                    not opr.get_attr("half_pixel_centers"):
                # jax.image.resize samples half-pixel centers (TF2
                # semantics); legacy TF1 grids would silently diverge.
                raise NotImplementedError(
                    f"{t} (node {opr.name}) only supports TF2 resize "
                    "semantics (half_pixel_centers=True, "
                    "align_corners=False)")
            b, _, _, c = args[0].shape
            return jax.image.resize(
                args[0], (b, size[0], size[1], c), method=method)
        if t == "OneHot":
            depth = int(np.asarray(args[1]))
            ax = int(opr.get_attr("axis"))
            on, off = args[2], args[3]
            oh = jax.nn.one_hot(args[0], depth,
                                axis=ax if ax != -1 else -1)
            return oh * on + (1 - oh) * off
        if t in ("Select", "SelectV2"):
            return jnp.where(args[0], args[1], args[2])
        if t == "Cast":
            dst = _jdt(opr.get_attr("DstT"))
            x = args[0]
            if isinstance(x, np.ndarray) or np.isscalar(x):
                return np.asarray(x).astype(dst)
            return x.astype(dst)

        if t == "MatMul":
            return _matmul(args[0], args[1],
                           opr.get_attr("transpose_a"),
                           opr.get_attr("transpose_b"))
        if t in ("BatchMatMul", "BatchMatMulV2", "BatchMatMulV3"):
            return _matmul(args[0], args[1],
                           opr.get_attr("adj_x"), opr.get_attr("adj_y"),
                           adjoint=True)
        if t == "Einsum":
            if self._gctx is not None:
                env, gplans = self._gctx
                plan = gplans.get(opr.name)
                if plan is not None:
                    from ..ops.flash_attention import bridge_flash_enabled
                    if bridge_flash_enabled():
                        out = _try_flash_attention(env, plan, opr)
                        if out is not None:
                            return out
            return _einsum_handler(opr, args)
        if t == "BiasAdd":
            return _bias_add(args[0], args[1],
                             opr.get_attr("data_format"))
        if t == "Conv2D":
            try:
                explicit = opr.get_attr("explicit_paddings")
            except ValueError:
                explicit = ()
            return _conv2d(args[0], args[1], opr.get_attr("strides"),
                           opr.get_attr("padding"),
                           opr.get_attr("dilations"),
                           opr.get_attr("data_format"), explicit)
        if t == "DepthwiseConv2dNative":
            return _depthwise_conv2d(
                args[0], args[1], opr.get_attr("strides"),
                opr.get_attr("padding"), opr.get_attr("dilations"),
                opr.get_attr("data_format"))
        if t == "MaxPool":
            return _pool(args[0], opr.get_attr("ksize"),
                         opr.get_attr("strides"),
                         opr.get_attr("padding"), "max")
        if t == "AvgPool":
            return _pool(args[0], opr.get_attr("ksize"),
                         opr.get_attr("strides"),
                         opr.get_attr("padding"), "avg")
        if t == "FusedBatchNormV3":
            return _fused_batch_norm(self, opr, *args[:5])
        if t == "SparseSoftmaxCrossEntropyWithLogits":
            return _sparse_softmax_ce(args[0], args[1])
        if t == "SoftmaxCrossEntropyWithLogits":
            return _softmax_ce(args[0], args[1])
        if t == "L2Loss":
            return jnp.sum(jnp.square(args[0])) / 2

        if t in _REDUCTIONS:
            return _REDUCTIONS[t](self, opr, args[0], args[1])
        if t == "ArgMax":
            return jnp.argmax(args[0], axis=int(np.asarray(args[1]))) \
                .astype(_jdt(opr.get_attr("output_type")))
        if t == "ArgMin":
            return jnp.argmin(args[0], axis=int(np.asarray(args[1]))) \
                .astype(_jdt(opr.get_attr("output_type")))

        simple = _SIMPLE_OPS.get(t)
        if simple is not None:
            return simple(*args)

        raise NotImplementedError(
            f"tf op {t!r} (node {opr.name}) has no jax mapping; add it "
            "to horovod_tpu/tensorflow/compile.py")


_SKIP = object()

_RANDOM_OPS = ("RandomUniform", "RandomStandardNormal",
               "StatelessRandomUniformV2", "StatelessRandomNormalV2")

_REDUCTIONS = {
    "Mean": _reduction("mean"), "Sum": _reduction("sum"),
    "Max": _reduction("max"), "Min": _reduction("min"),
    "Prod": _reduction("prod"), "All": _reduction("all"),
    "Any": _reduction("any"),
}


def _make_simple_ops():
    import jax
    jnp = _jnp()

    def binop(fn, fn_static=None):
        # Static operands (shape math) stay numpy — omnistaging would
        # stage a jnp call on constants into the trace.
        def h(a, b):
            if _is_static(a) and _is_static(b):
                return np.asarray((fn_static or fn)(np.asarray(a),
                                                    np.asarray(b)))
            return fn(a, b)
        return h

    return {
        "Add": binop(lambda a, b: a + b),
        "AddV2": binop(lambda a, b: a + b),
        "Sub": binop(lambda a, b: a - b),
        "Mul": binop(lambda a, b: a * b),
        "RealDiv": binop(lambda a, b: a / b),
        "Div": binop(lambda a, b: a / b),
        "FloorDiv": binop(lambda a, b: a // b),
        "FloorMod": binop(lambda a, b: a % b),
        "Pow": binop(jnp.power, np.power),
        "Maximum": binop(jnp.maximum, np.maximum),
        "Minimum": binop(jnp.minimum, np.minimum),
        "SquaredDifference": lambda a, b: jnp.square(a - b),
        # Safe-denominator form: a plain where(b==0, 0, a/b) yields NaN
        # *gradients* at b==0 (inf cotangent times zero), the classic
        # JAX where-div pitfall.
        "DivNoNan": lambda a, b: jnp.where(
            b == 0, 0.0, a / jnp.where(b == 0, 1, b)),
        "AddN": lambda *xs: sum(xs[1:], start=xs[0]),
        "Square": jnp.square, "Sqrt": jnp.sqrt,
        "Rsqrt": lambda x: 1.0 / jnp.sqrt(x),
        "Exp": jnp.exp, "Log": jnp.log, "Log1p": jnp.log1p,
        "Expm1": jnp.expm1,
        "Neg": lambda x: -x, "Abs": jnp.abs, "Sign": jnp.sign,
        "Floor": jnp.floor, "Ceil": jnp.ceil, "Round": jnp.round,
        "Rint": jnp.round,
        "Tanh": jnp.tanh, "Sigmoid": jax.nn.sigmoid,
        "Erf": jax.scipy.special.erf,
        "Erfc": jax.scipy.special.erfc,
        "Erfinv": jax.scipy.special.erfinv,
        "Sin": jnp.sin, "Cos": jnp.cos,
        "Sinh": jnp.sinh, "Cosh": jnp.cosh,
        "Atan2": jnp.arctan2,
        "Relu": jax.nn.relu,
        "Relu6": lambda x: jnp.clip(x, 0, 6),
        "LeakyRelu": jax.nn.leaky_relu,
        "Elu": jax.nn.elu, "Selu": jax.nn.selu,
        "Softplus": jax.nn.softplus,
        "Softsign": jax.nn.soft_sign,
        "Softmax": lambda x: jax.nn.softmax(
            x.astype(jnp.float32), axis=-1).astype(x.dtype),
        "LogSoftmax": lambda x: jax.nn.log_softmax(
            x.astype(jnp.float32), axis=-1).astype(x.dtype),
        "Equal": binop(lambda a, b: a == b),
        "NotEqual": binop(lambda a, b: a != b),
        "Less": binop(lambda a, b: a < b),
        "LessEqual": binop(lambda a, b: a <= b),
        "Greater": binop(lambda a, b: a > b),
        "GreaterEqual": binop(lambda a, b: a >= b),
        "LogicalAnd": binop(lambda a, b: a & b),
        "LogicalOr": binop(lambda a, b: a | b),
        "LogicalNot": lambda x: ~x,
        "ClipByValue": jnp.clip,
        "Reciprocal": lambda x: 1.0 / x,
        "IsFinite": jnp.isfinite,
        "IsNan": jnp.isnan,
        "IsInf": jnp.isinf,
    }


_SIMPLE_OPS = None


def _init_tables():
    global _SIMPLE_OPS
    if _SIMPLE_OPS is None:
        _SIMPLE_OPS = _make_simple_ops()


class CompiledFunction:
    """A tf.function compiled to a jitted JAX callable.

    ``params`` holds the trainable variables (flat name->jax-array dict —
    the pytree the train step updates); ``buffers`` holds non-trainable
    ones (e.g. batch-norm moving stats), functionally updated from the
    graph's Assign ops after each training call."""

    def __init__(self, cf, params, buffers, capture_values, fdefs,
                 compute_dtype=None, verify=False):
        _init_tables()
        self._cf = cf
        self._interp = _GraphInterpreter(cf.graph, capture_values, fdefs)
        self.params = params
        self.buffers = buffers
        self.compute_dtype = compute_dtype
        self.verify = verify
        self._jitted = {}

    # -- functional core ---------------------------------------------------
    def apply(self, params, inputs, buffers=None, rng=None,
              training=False):
        """Pure forward: returns (structured_output, new_buffers).
        Differentiable w.r.t. ``params``.

        With ``compute_dtype`` set (the torch bridge's XLA_USE_BF16
        analog), float params AND float inputs are cast on entry:
        master weights and gradients stay fp32 while convs/matmuls ride
        the MXU in bf16 — BatchNorm/softmax/CE handlers already compute
        their statistics in fp32 internally."""
        import tensorflow as tf
        buffers = self.buffers if buffers is None else buffers
        if self.compute_dtype is not None:
            jnp = _jnp()

            def cast(v):
                if hasattr(v, "dtype") and jnp.issubdtype(
                        jnp.asarray(v).dtype, jnp.floating):
                    return jnp.asarray(v).astype(self.compute_dtype)
                return v

            params = {k: cast(v) for k, v in params.items()}
            inputs = [cast(v) for v in inputs]
        flat, updates = self._interp.run(params, buffers, list(inputs),
                                         rng=rng, training=training)
        out = tf.nest.pack_sequence_as(self._cf.structured_outputs, flat)
        new_buffers = dict(buffers)
        new_buffers.update(updates)
        return out, new_buffers

    def __call__(self, *inputs, rng=None, training=False):
        import jax
        sig = (training, rng is not None, len(inputs))
        inputs = tuple(self._coerce(v) for v in inputs)
        if sig not in self._jitted:
            def fwd(params, buffers, inputs, rng):
                out, _ = self.apply(params, inputs, buffers=buffers,
                                    rng=rng, training=training)
                return out
            if self.verify:
                # hvd-lint jaxpr layer over the rebuilt graph before it
                # is jitted: once per signature, trace-only.
                from .. import analysis
                analysis.verify_traceable(
                    fwd, (self.params, self.buffers, inputs, rng),
                    mode=self.verify, what="tf-bridge forward")
            self._jitted[sig] = jax.jit(fwd)
        return self._jitted[sig](self.params, self.buffers, inputs, rng)

    @staticmethod
    def _coerce(v):
        import jax.numpy as jnp
        if hasattr(v, "numpy") and not hasattr(v, "devices"):  # tf tensor
            return jnp.asarray(_np_narrow(v.numpy()))
        if isinstance(v, np.ndarray):
            return jnp.asarray(_np_narrow(v))
        return v

    def make_train_step(self, optimizer, process_set=None):
        """Jitted distributed train step: forward+backward on the chip,
        gradient reduction through the JAX binding, optax update, buffer
        (e.g. BN moving-stat) writes applied. The compiled function must
        return a scalar loss (or a structure whose first flat element is
        the scalar loss). Returns ``step(batch, rng=None) -> loss`` with
        params/opt state living inside (TF-optimizer style)."""
        import jax
        from .. import basics
        from .. import jax as hvd_jax

        dist_opt = optimizer
        if not hasattr(dist_opt, "inner"):  # bare optax transform
            dist_opt = hvd_jax.DistributedOptimizer(
                optimizer, **({"process_set": process_set}
                              if process_set else {}))

        def loss_fn(params, aux, batch):
            import tensorflow as tf
            inputs, rng = batch
            out, new_buffers = self.apply(
                params, inputs, buffers=aux,
                rng=None if rng is None else rng[0], training=True)
            flat = tf.nest.flatten(out)
            loss = flat[0]
            if getattr(loss, "ndim", 0) != 0:
                raise ValueError(
                    "make_train_step needs a scalar loss as the "
                    f"function's (first) output; got shape "
                    f"{getattr(loss, 'shape', None)}")
            return loss, new_buffers

        step = hvd_jax.make_train_step(loss_fn, dist_opt, has_aux=True)
        opt_state = dist_opt.init(self.params)
        state = {"opt": opt_state}

        def run(batch, rng=None):
            batch = tuple(self._coerce(v) for v in batch)
            rt = basics.runtime()
            n = int(rt.mesh.shape[hvd_jax.HVD_AXIS])
            for i, v in enumerate(batch):
                if hasattr(v, "shape") and (v.ndim == 0
                                            or v.shape[0] % n):
                    raise ValueError(
                        f"batch[{i}] leading axis {v.shape} must be "
                        f"divisible by the local mesh size {n}: the step "
                        "shards the batch across this runtime's devices")
            if rng is not None:
                rng = jax.random.fold_in(rng, rt.topology.rank)
                rng = jax.random.split(rng, n)
            new_params, new_buffers, new_opt, loss_val = step(
                self.params, self.buffers, state["opt"], (batch, rng))
            self.params = new_params
            self.buffers = new_buffers
            state["opt"] = new_opt
            return loss_val

        return run

    def copy_params_to_variables(self, variables=None):
        """Write the (possibly updated) jax values back into the TF
        variables, so TF-side checkpointing/eval sees trained weights."""
        import jax
        variables = self._cf.variables if variables is None else variables
        for v in variables:
            src = self.params.get(v.name, self.buffers.get(v.name))
            if src is not None:
                v.assign(np.asarray(jax.device_get(src),
                                    dtype=v.dtype.as_numpy_dtype))


def tpu_compile(fn, example_inputs=None, input_signature=None,
                dynamic_batch=True, compute_dtype=None, verify=False):
    """Compile a TF2 callable for TPU execution via graph→JAX.

    Args:
      fn: a python callable using TF ops, or a ``tf.function``. Model
        variables must be captured (module attributes / closure), the TF2
        idiom.
      example_inputs: concrete example arguments (tensors/arrays) used to
        trace. With ``dynamic_batch`` (default) the leading dim is traced
        as None so ``tf.shape``-based batch math stays symbolic — the
        train step re-specializes it per batch shard, while every other
        dim stays static as XLA requires.
      input_signature: alternative to example_inputs — a list of
        ``tf.TensorSpec`` (None dims allowed; they resolve to the actual
        jax shapes at interpretation time).
      verify: run the hvd-lint jaxpr analyzer over each signature before
        jitting (True: raise on error-severity findings; ``"warn"``:
        log only) — see docs/lint.md.

    Returns a :class:`CompiledFunction`.
    """
    import tensorflow as tf

    if not isinstance(fn, def_function_type()):
        fn = tf.function(fn)
    if input_signature is not None:
        cf = fn.get_concrete_function(*input_signature)
    elif example_inputs is not None:
        specs = []
        for a in example_inputs:
            shape = list(np.shape(a))
            if dynamic_batch and shape:
                # Keep the batch dim symbolic: a fully-static trace would
                # constant-fold tf.shape into the trace-time batch size,
                # which breaks when shard_map hands each device 1/N of
                # the batch.
                shape[0] = None
            specs.append(tf.TensorSpec(shape, tf.as_dtype(
                np.asarray(a).dtype if not tf.is_tensor(a) else a.dtype)))
        cf = fn.get_concrete_function(*specs)
    else:
        raise ValueError("pass example_inputs or input_signature")

    params, buffers, capture_values = {}, {}, {}
    seen_names = set()
    # Hold (handle, variable) pairs simultaneously: matching must be by
    # object identity against the graph's captured external tensor, and
    # an id()-keyed dict without live references can alias a GC'd
    # temporary's id onto another variable — silently swapping
    # same-shaped variables (e.g. BN moving mean/variance).
    handles = []
    for v in cf.variables:
        if v.name in seen_names:
            raise ValueError(f"duplicate variable name {v.name}")
        seen_names.add(v.name)
        handles.append((v.handle, v.name))
        target = params if v.trainable else buffers
        target[v.name] = _jnp().asarray(_np_narrow(v.numpy()))
    for ext, internal in cf.graph.captures:
        if ext.dtype == tf.resource:
            name = next((nm for h, nm in handles if h is ext), None)
            if name is None:
                raise NotImplementedError(
                    f"captured resource {internal.name} is not a model "
                    "variable (tables/iterators are out of scope)")
            capture_values[internal.name] = _Var(name)
        else:
            capture_values[internal.name] = _jnp().asarray(
                _np_narrow(ext.numpy()))

    fdefs = {f.signature.name: f
             for f in cf.graph.as_graph_def().library.function}
    return CompiledFunction(cf, params, buffers, capture_values, fdefs,
                            compute_dtype=compute_dtype, verify=verify)


def def_function_type():
    import tensorflow as tf
    return type(tf.function(lambda: None))
