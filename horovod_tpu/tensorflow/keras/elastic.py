"""Elastic Keras state + callbacks under the ``horovod.tensorflow.keras``
namespace (reference: horovod/tensorflow/keras/elastic.py:22 KerasState,
:34-70 elastic callbacks).
"""

from ...elastic import run  # noqa: F401
from ..elastic import TensorFlowKerasState


class KerasState(TensorFlowKerasState):
    """State of a Keras model and optimizer for elastic training
    (reference: horovod/tensorflow/keras/elastic.py:22)."""


def __getattr__(name):
    """Lazy class creation, cached in module globals so repeated access
    returns the SAME class (isinstance/identity checks must hold)."""
    from ..._keras.elastic import make_elastic_callbacks
    (commit, upd_batch, upd_epoch) = make_elastic_callbacks()
    mapping = {
        "CommitStateCallback": commit,
        "UpdateBatchStateCallback": upd_batch,
        "UpdateEpochStateCallback": upd_epoch,
    }
    if name in mapping:
        globals().update(mapping)
        return globals()[name]
    raise AttributeError(name)
