"""Keras callback set under the ``horovod.tensorflow.keras`` namespace
(reference: horovod/tensorflow/keras/callbacks.py:22-151). The classes
are the shared backend-agnostic implementations (.._keras.callbacks),
bound lazily so importing this module never imports keras.
"""


def _make():
    from ..._keras.callbacks import make_callbacks
    return make_callbacks()


def _best_model_checkpoint():
    import keras

    class BestModelCheckpoint(keras.callbacks.ModelCheckpoint):
        """save_best_only ModelCheckpoint (reference:
        horovod/tensorflow/keras/callbacks.py:151 — used by the Spark
        estimator to keep the best epoch's weights; ``filepath`` may be
        assigned after construction, as the reference does)."""

        def __init__(self, filepath=None, monitor="val_loss", verbose=0,
                     save_weights_only=False, mode="auto",
                     save_freq="epoch"):
            super().__init__(filepath=filepath or "", monitor=monitor,
                             verbose=verbose, save_best_only=True,
                             save_weights_only=save_weights_only,
                             mode=mode, save_freq=save_freq)

    return BestModelCheckpoint


def __getattr__(name):
    """Lazy class creation, cached in module globals so repeated access
    returns the SAME class (isinstance/identity checks must hold)."""
    (bgv, ma, warmup, sched) = _make()
    mapping = {
        "BroadcastGlobalVariablesCallback": bgv,
        "MetricAverageCallback": ma,
        "LearningRateWarmupCallback": warmup,
        "LearningRateScheduleCallback": sched,
    }
    if name == "BestModelCheckpoint":
        mapping[name] = _best_model_checkpoint()
    if name in mapping:
        globals().update(mapping)
        return globals()[name]
    raise AttributeError(name)
