"""Keras callback set under the ``horovod.tensorflow.keras`` namespace
(reference: horovod/tensorflow/keras/callbacks.py:22-151). The classes
are the shared backend-agnostic implementations (.._keras.callbacks),
bound lazily so importing this module never imports keras.
"""


def _make():
    from ..._keras.callbacks import make_callbacks
    return make_callbacks()


def _best_model_checkpoint():
    import keras

    class BestModelCheckpoint(keras.callbacks.ModelCheckpoint):
        """save_best_only ModelCheckpoint (reference:
        horovod/tensorflow/keras/callbacks.py:151 — used by the Spark
        estimator to keep the best epoch's weights; ``filepath`` may be
        assigned after construction, as the reference does)."""

        def __init__(self, filepath=None, monitor="val_loss", verbose=0,
                     save_weights_only=False, mode="auto",
                     save_freq="epoch"):
            super().__init__(filepath=filepath or "", monitor=monitor,
                             verbose=verbose, save_best_only=True,
                             save_weights_only=save_weights_only,
                             mode=mode, save_freq=save_freq)

    return BestModelCheckpoint


_LAZY = ("BroadcastGlobalVariablesCallback", "MetricAverageCallback",
         "LearningRateWarmupCallback", "LearningRateScheduleCallback")


def __getattr__(name):
    """Lazy class creation, cached in module globals so repeated access
    returns the SAME class (isinstance/identity checks must hold). The
    name check comes FIRST so attribute probes for other names raise
    AttributeError without importing keras."""
    if name == "BestModelCheckpoint":
        cls = _best_model_checkpoint()
        globals()[name] = cls
        return cls
    if name not in _LAZY:
        raise AttributeError(name)
    globals().update(zip(_LAZY, _make()))
    return globals()[name]
