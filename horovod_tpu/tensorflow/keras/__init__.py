"""``horovod_tpu.tensorflow.keras`` — drop-in surface of the reference's
``horovod.tensorflow.keras`` (reference:
horovod/tensorflow/keras/__init__.py:49 DistributedOptimizer, :141-216
collective wrappers/load_model).

In this image ``tf.keras`` *is* Keras 3 (TF >= 2.16 re-exports it), so the
implementation is the shared multi-backend binding (.._keras): gradient
sync rides the host plane for eager/graph steps, and the compiled on-chip
path is ``horovod_tpu.keras.set_data_parallel`` with KERAS_BACKEND=jax.
This module exists so reference scripts written against
``import horovod.tensorflow.keras as hvd`` keep working verbatim.
"""

from ... import basics
from ...ops import reduce_ops
from ...ops.compression import Compression  # noqa: F401
from ...process_sets import (ProcessSet, global_process_set,  # noqa: F401
                             add_process_set, remove_process_set)
from ..._keras import create_distributed_optimizer, rank, size, spmd_active
from .. import (start_timeline, stop_timeline)  # noqa: F401
from ...keras import (set_data_parallel, load_model,  # noqa: F401
                      allreduce, allgather, broadcast,
                      broadcast_global_variables)
from . import callbacks  # noqa: F401
from . import elastic  # noqa: F401

Average = reduce_ops.Average
Sum = reduce_ops.Sum
Adasum = reduce_ops.Adasum

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
local_rank = basics.local_rank
local_size = basics.local_size
cross_rank = basics.cross_rank
cross_size = basics.cross_size
mpi_enabled = basics.mpi_enabled
gloo_enabled = basics.gloo_enabled
nccl_built = basics.nccl_built

__all__ = ["init", "shutdown", "rank", "size", "local_rank", "local_size",
           "cross_rank", "cross_size", "DistributedOptimizer",
           "broadcast_global_variables", "allreduce", "allgather",
           "broadcast", "load_model", "set_data_parallel", "callbacks",
           "elastic", "Compression", "Average", "Sum", "Adasum"]


def DistributedOptimizer(optimizer, name=None,
                         device_dense="", device_sparse="",
                         compression=None,
                         sparse_as_dense=False,
                         gradient_predivide_factor=1.0,
                         op=Average,
                         backward_passes_per_step=1,
                         average_aggregated_gradients=False,
                         num_groups=0,
                         groups=None,
                         process_set=global_process_set):
    """Reference: horovod/tensorflow/keras/__init__.py:49 (full kwarg
    surface, including the reference's num_groups→groups deprecation).

    ``compression`` applies on the host/eager sync planes.
    ``device_dense``/``device_sparse`` are GPU placement in the
    reference — inert here (XLA owns placement); ``sparse_as_dense``
    likewise (the sync plane always densifies). ``num_groups`` (or an
    integer ``groups``) splits each sync into that many fusion buckets
    — one grouped collective per bucket; the list-of-variable-lists
    ``groups`` spelling needs the variable identities at sync time,
    which the keras-3 apply path does not expose — use
    horovod_tpu.tensorflow.DistributedOptimizer for that spelling.
    """
    import warnings
    import keras
    if op not in (Average, Sum, Adasum):
        raise ValueError("op currently only supports Average, Sum, Adasum")
    if num_groups != 0:
        warnings.warn("Parameter `num_groups` has been replaced by "
                      "`groups` (reference deprecation).",
                      DeprecationWarning)
        if groups is None:
            groups = num_groups
    if groups is not None and not (isinstance(groups, list) or groups > 0):
        raise ValueError("groups should be a non-negative integer or a "
                         "list of lists of variables.")
    if isinstance(groups, list):
        raise NotImplementedError(
            "the list-of-variable-lists `groups` spelling is not "
            "supported on the keras-3 apply path (variable identities "
            "are not visible at sync time); pass an integer bucket "
            "count, or use horovod_tpu.tensorflow.DistributedOptimizer "
            "which supports explicit variable groups.")
    if process_set is not global_process_set:
        raise NotImplementedError(
            "keras DistributedOptimizer syncs over the global process "
            "set; build per-set training loops with "
            "horovod_tpu.tensorflow.DistributedOptimizer instead.")
    return create_distributed_optimizer(
        keras, optimizer, name=name, op=op,
        gradient_predivide_factor=gradient_predivide_factor,
        backward_passes_per_step=backward_passes_per_step,
        average_aggregated_gradients=average_aggregated_gradients,
        compression=compression, num_groups=int(groups or 0))
