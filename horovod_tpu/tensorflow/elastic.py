"""TensorFlow/Keras elastic state (reference:
horovod/tensorflow/elastic.py:221 ``TensorFlowKerasState``).

Holds in-memory snapshots of a Keras model's weights (and optimizer
variables) plus user scalars; ``sync()`` re-broadcasts from the new rank 0
after an elastic reset.
"""

import copy

import numpy as np

from ..elastic import State
from ..functions import broadcast_object, broadcast_variables


def _get_opt_weights(optimizer):
    if optimizer is None:
        return None
    try:
        return [np.asarray(v) for v in optimizer.variables]
    except (AttributeError, TypeError):
        return None


def _set_opt_weights(optimizer, weights):
    if optimizer is None or weights is None:
        return
    for var, w in zip(optimizer.variables, weights):
        var.assign(w)


class TensorFlowKerasState(State):
    """Elastic state for a Keras model (+ optimizer) and scalars."""

    def __init__(self, model, optimizer=None, **kwargs):
        super().__init__()
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        self._scalars = list(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        self._saved = None
        self.save()

    def _scalar_state(self):
        return {k: getattr(self, k) for k in self._scalars}

    def save(self):
        self._saved = {
            "weights": [np.array(w) for w in self.model.get_weights()],
            "opt": _get_opt_weights(self.optimizer),
            "scalars": copy.deepcopy(self._scalar_state()),
        }

    def restore(self):
        self.model.set_weights([np.array(w)
                                for w in self._saved["weights"]])
        _set_opt_weights(self.optimizer, self._saved["opt"])
        for k, v in self._saved["scalars"].items():
            # Deepcopy on the way OUT too: handing the snapshot's mutable
            # objects to the user by reference would let later in-place
            # edits corrupt the committed state.
            setattr(self, k, copy.deepcopy(v))

    def sync(self):
        weights = [np.asarray(w) for w in self.model.get_weights()]
        synced = broadcast_variables(weights, root_rank=0)
        self.model.set_weights([np.asarray(w) for w in synced])
        payload = {
            "opt": _get_opt_weights(self.optimizer),
            "scalars": self._scalar_state(),
        }
        synced_payload = broadcast_object(payload, root_rank=0,
                                          name="tf_elastic_state")
        _set_opt_weights(self.optimizer, synced_payload["opt"])
        for k, v in synced_payload["scalars"].items():
            setattr(self, k, v)
        self.save()
