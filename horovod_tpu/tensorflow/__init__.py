"""TensorFlow 2 binding: drop-in surface of the reference's
``horovod.tensorflow`` (reference: horovod/tensorflow/__init__.py:29-43,
mpi_ops.py) on the horovod_tpu runtime.

Process-level semantics, exactly like the reference: one process per
accelerator (launched by ``hvdrun``), ``rank()/size()`` come from the
launcher topology, and collectives ride the SPMD data plane (TCP fallback
or the XLA global mesh, backend/xla_global.py). Inside ``tf.function``
graphs the ops run through ``tf.py_function`` — the host-side enqueue is
the same boundary the reference crosses with its custom-op kernels
(reference: horovod/tensorflow/mpi_ops.cc:431 ComputeAsync).
"""

import numpy as np

from .. import basics
from ..ops import reduce_ops
from ..ops import collectives as _c
from ..ops.compression import Compression
from ..process_sets import (ProcessSet, global_process_set,
                            add_process_set, remove_process_set)
from ..utils.logging_util import get_logger

Average = reduce_ops.Average
Sum = reduce_ops.Sum
Adasum = reduce_ops.Adasum
Min = reduce_ops.Min
Max = reduce_ops.Max
Product = reduce_ops.Product

init = basics.init
shutdown = basics.shutdown
is_initialized = basics.is_initialized
local_rank = basics.local_rank
local_size = basics.local_size
cross_rank = basics.cross_rank
cross_size = basics.cross_size
is_homogeneous = basics.is_homogeneous
mpi_enabled = basics.mpi_enabled
mpi_built = basics.mpi_built
mpi_threads_supported = basics.mpi_threads_supported
gloo_enabled = basics.gloo_enabled
gloo_built = basics.gloo_built
nccl_built = basics.nccl_built
ddl_built = basics.ddl_built
ccl_built = basics.ccl_built
cuda_built = basics.cuda_built
rocm_built = basics.rocm_built
metrics_snapshot = basics.metrics_snapshot

from . import elastic  # noqa: E402,F401  (hvd.elastic.TensorFlowKerasState)


def gpu_available():
    """Reference: horovod/tensorflow/__init__.py gpu_available — here
    'accelerator available': True when a TPU (or other non-CPU XLA
    device) backs the runtime."""
    import jax
    return any(d.platform != "cpu" for d in jax.devices())


def check_num_rank_power_of_2(num_ranks):
    """Reference: horovod/tensorflow/__init__.py:138-154 (Adasum's
    power-of-2 rank requirement)."""
    if num_ranks == 0 or num_ranks & (num_ranks - 1):
        raise ValueError(
            "Adasum allreduce requires a power-of-2 number of ranks; "
            f"got {num_ranks}")


def start_timeline(file_path, mark_cycles=None, jax_profiler_dir=None):
    """Reference: horovod/common/basics.py:156 start_timeline."""
    from .. import start_timeline as _st
    return _st(file_path, mark_cycles=mark_cycles,
               jax_profiler_dir=jax_profiler_dir)


def stop_timeline():
    from .. import stop_timeline as _st
    return _st()


def _tf():
    import tensorflow as tf
    return tf


def rank():
    """Process-level rank (launcher topology, not virtual devices)."""
    return basics.runtime().topology.rank


def size():
    return basics.runtime().topology.size


def _spmd():
    """True when collectives actually span processes. In single-controller
    mode this binding behaves as world size 1 — per-process drop-in
    scripts use hvdrun (the compiled per-device path lives in
    horovod_tpu.jax instead)."""
    rt = basics.runtime()
    return rt.mode == basics.MODE_SPMD and rt.topology.size > 1


# Graph-op variants (reference: horovod/tensorflow/mpi_ops.py:410-472
# rank/size query ops usable inside graphs). Under ELASTIC mode they
# resolve at graph EXECUTION time (py_function) — the runtime re-forms
# with new ranks/sizes on membership changes, so a tf.function that
# captured one must observe the NEW value after a reset. Outside elastic
# mode rank/size genuinely are fixed for the process lifetime, and a
# tf.constant keeps jit_compile=True / SavedModel export working
# (EagerPyFunc is neither XLA-compilable nor serializable).
def _runtime_scalar_op(fn, name):
    tf = _tf()
    from ..utils import envparse
    if not envparse.get_bool(envparse.ELASTIC):
        return tf.constant(np.int32(fn()), name=name)

    def _value():
        return np.int32(fn())

    out = tf.py_function(_value, [], tf.int32, name=name)
    out.set_shape(())
    return out


def _process_set_size(process_set_id):
    if process_set_id in (0, None):
        return size()
    from ..process_sets import process_set_by_id
    ps = process_set_by_id(process_set_id)
    if ps is None:
        raise ValueError(f"no process set with id {process_set_id}")
    return len(ps.ranks)


def rank_op(name=None):
    return _runtime_scalar_op(rank, name or "horovod_rank")


def local_rank_op(name=None):
    return _runtime_scalar_op(local_rank, name or "horovod_local_rank")


def size_op(process_set_id=0, name=None):
    # the default name carries the ps id so the graph→JAX bridge can
    # resolve the op without access to the captured python closure
    return _runtime_scalar_op(
        lambda: _process_set_size(process_set_id),
        name or f"horovod_size_ps{process_set_id}")


def local_size_op(name=None):
    return _runtime_scalar_op(local_size, name or "horovod_local_size")


def process_set_included_op(process_set=global_process_set, name=None):
    """1 when this rank belongs to process_set, else 0 (reference:
    horovod/tensorflow/mpi_ops.py process_set_included_op). Accepts a
    ProcessSet object or a numeric process_set_id."""
    def _included():
        ps = process_set
        if isinstance(ps, int):
            from ..process_sets import process_set_by_id
            ps = process_set_by_id(process_set)
            if ps is None:
                raise ValueError(f"no process set with id {process_set}")
        return 1 if ps.included() else 0

    ps_id = (process_set if isinstance(process_set, int)
             else process_set.process_set_id)
    return _runtime_scalar_op(
        _included, name or f"horovod_process_set_included_ps{ps_id}")


def _np_of(tensor):
    tf = _tf()
    if isinstance(tensor, np.ndarray):
        return tensor
    return tensor.numpy() if hasattr(tensor, "numpy") else np.asarray(
        tf.convert_to_tensor(tensor))


def _eager(fn, tensors, out_dtypes, name, shape_preserving=False):
    """Run fn (numpy -> list[numpy]) now if eager, else via py_function so
    it works inside tf.function graphs. Results are cast back to
    out_dtypes: the data plane runs x64-off, so float64/int64 inputs come
    back narrowed and the reference contract (result dtype == input
    dtype) must be restored here.

    shape_preserving: for ops whose output shape equals the input shape
    (allreduce/broadcast families), re-attach the static shapes that
    py_function erases — keras-3's optimizer engine calls
    ``grad.shape.as_list()`` and chokes on unknown shapes otherwise."""
    tf = _tf()

    def restore(outs):
        return [tf.cast(tf.convert_to_tensor(o), dt)
                for o, dt in zip(outs, out_dtypes)]

    if tf.executing_eagerly():
        return restore(fn([_np_of(t) for t in tensors]))

    def wrapper(*args):
        return restore(fn([a.numpy() for a in args]))

    outs = tf.py_function(func=wrapper, inp=list(tensors),
                          Tout=out_dtypes)
    if shape_preserving:
        outs = [tf.ensure_shape(o, tf.convert_to_tensor(t).shape)
                for o, t in zip(outs, tensors)]
    return outs


def _result_np(x):
    return np.asarray(x)


def allreduce(tensor, average=None, device_dense="", device_sparse="",
              compression=None, op=None, prescale_factor=1.0,
              postscale_factor=1.0, name=None,
              process_set=global_process_set):
    """Reference: horovod/tensorflow/__init__.py:55-161 ``allreduce``.
    IndexedSlices are densified (the reference's ``sparse_as_dense``
    behavior) before reduction. ``compression`` shrinks the bytes the
    host data plane carries (fp16/bf16 cast before the collective, cast
    back after), like the reference's wire compression."""
    tf = _tf()
    if op is None:
        op = Sum if average is False else Average
    if compression is None:
        compression = Compression.none
    if isinstance(tensor, tf.IndexedSlices):
        tensor = tf.convert_to_tensor(tensor)
    if not _spmd():
        scale = prescale_factor * postscale_factor
        return tensor * scale if scale != 1.0 else tf.convert_to_tensor(
            tensor)

    def fn(arrs):
        out = _c.allreduce(arrs[0], op=op, name=name,
                           compression=compression,
                           prescale_factor=prescale_factor,
                           postscale_factor=postscale_factor,
                           process_set=process_set)
        return [_result_np(out)]

    return _eager(fn, [tensor], [tensor.dtype], name,
                  shape_preserving=True)[0]


def grouped_allreduce(tensors, average=None, op=None, prescale_factor=1.0,
                      postscale_factor=1.0, name=None, compression=None,
                      process_set=global_process_set):
    if op is None:
        op = Sum if average is False else Average
    comp = Compression.none if compression is None else compression
    if not _spmd():
        tf = _tf()
        scale = prescale_factor * postscale_factor
        return [t * scale if scale != 1.0 else tf.convert_to_tensor(t)
                for t in tensors]

    def fn(arrs):
        outs = _c.grouped_allreduce(arrs, op=op, name=name,
                                    compression=comp,
                                    prescale_factor=prescale_factor,
                                    postscale_factor=postscale_factor,
                                    process_set=process_set)
        return [_result_np(o) for o in outs]

    return _eager(fn, tensors, [t.dtype for t in tensors], name,
                  shape_preserving=True)


def allgather(tensor, name=None, process_set=global_process_set):
    if not _spmd():
        return _tf().convert_to_tensor(tensor)

    def fn(arrs):
        return [_result_np(_c.allgather(arrs[0], name=name,
                                        process_set=process_set))]

    return _eager(fn, [tensor], [tensor.dtype], name)[0]


def broadcast(tensor, root_rank, name=None,
              process_set=global_process_set):
    if not _spmd():
        return _tf().convert_to_tensor(tensor)

    def fn(arrs):
        return [_result_np(_c.broadcast(arrs[0], root_rank, name=name,
                                        process_set=process_set))]

    return _eager(fn, [tensor], [tensor.dtype], name,
                  shape_preserving=True)[0]


def alltoall(tensor, splits=None, name=None,
             process_set=global_process_set):
    tf = _tf()
    if not _spmd():
        out = tf.convert_to_tensor(tensor)
        if splits is None:
            return out
        return out, tf.convert_to_tensor(np.asarray(splits))

    if splits is None:
        def fn(arrs):
            return [_result_np(_c.alltoall(arrs[0], None, name=name,
                                           process_set=process_set))]
        return _eager(fn, [tensor], [tensor.dtype], name)[0]

    def fn(arrs):
        out, rsplits = _c.alltoall(arrs[0], arrs[1], name=name,
                                   process_set=process_set)
        return [_result_np(out), np.asarray(rsplits, np.int32)]

    outs = _eager(fn, [tensor, tf.cast(splits, tf.int32)],
                  [tensor.dtype, tf.int32], name)
    return outs[0], outs[1]


def reducescatter(tensor, op=None, name=None,
                  process_set=global_process_set):
    if not _spmd():
        return _tf().convert_to_tensor(tensor)

    def fn(arrs):
        return [_result_np(_c.reducescatter(arrs[0], op=op or Average,
                                            name=name,
                                            process_set=process_set))]

    return _eager(fn, [tensor], [tensor.dtype], name)[0]


def broadcast_(variables, root_rank, name=None,
               process_set=global_process_set):
    """In-place broadcast into tf.Variables (reference:
    horovod/tensorflow/mpi_ops.py:301 ``broadcast_(variables, ...)`` —
    takes a LIST of variables; a single variable is accepted too).
    Returns the updated values (list in, list out)."""
    single = not isinstance(variables, (list, tuple))
    var_list = [variables] if single else list(variables)
    outs = []
    for i, v in enumerate(var_list):
        nm = f"{name}.{i}" if name and not single else name
        out = broadcast(v.read_value() if hasattr(v, "read_value")
                        else v, root_rank, name=nm,
                        process_set=process_set)
        v.assign(out)
        outs.append(v)
    return outs[0] if single else outs


def broadcast_object(obj, root_rank=0, name=None):
    from ..functions import broadcast_object as _bo
    return _bo(obj, root_rank=root_rank, name=name)


def broadcast_object_fn(root_rank=0, name=None):
    """Reference: horovod/tensorflow/functions.py broadcast_object_fn —
    returns a callable capturing root_rank/name."""
    def _fn(obj):
        return broadcast_object(obj, root_rank=root_rank, name=name)
    return _fn


def allgather_object(obj, name=None):
    from ..functions import allgather_object as _ao
    return _ao(obj, name=name)


def broadcast_variables(variables, root_rank=0):
    """Assign every variable its root-rank value (fused broadcast;
    reference: horovod/tensorflow/functions.py:66). Works inside
    tf.function graphs too — the reference examples call it from a
    @tf.function training step (reference:
    examples/tensorflow2/tensorflow2_mnist.py:75), so the host-side
    exchange rides tf.py_function there, like every collective in this
    binding."""
    tf = _tf()
    from ..functions import broadcast_variables as _bv
    variables = list(variables)
    if not variables or not _spmd():
        return

    def assign_all(arrays):
        outs = _bv(arrays, root_rank=root_rank)
        for v, out in zip(variables, outs):
            # keras-3 variables report dtype as a STRING; normalize.
            np_dtype = tf.as_dtype(v.dtype).as_numpy_dtype
            v.assign(np.asarray(out).astype(np_dtype, copy=False))
        return [np.int32(0)]

    if tf.executing_eagerly():
        assign_all([v.numpy() for v in variables])
        return

    def wrapper(*args):
        assign_all([a.numpy() for a in args])
        return tf.constant(0, tf.int32)

    tf.py_function(func=wrapper,
                   inp=[tf.convert_to_tensor(v) for v in variables],
                   Tout=[tf.int32])


def join(device=-1):
    if not _spmd():
        return -1
    return _c.join(device)


def barrier(process_set=global_process_set):
    if not _spmd():
        return
    return _c.barrier(process_set=process_set)


class DistributedGradientTape:
    """tf.GradientTape wrapper averaging gradients across ranks
    (reference: horovod/tensorflow/__init__.py:777)."""

    def __init__(self, gradtape, device_dense="", device_sparse="",
                 compression=None, sparse_as_dense=True, op=Average,
                 gradient_predivide_factor=1.0,
                 num_groups=0, groups=None,
                 process_set=global_process_set):
        self._tape = gradtape
        self._op = op
        self._process_set = process_set
        self._predivide = gradient_predivide_factor
        self._num_groups = num_groups
        self._groups = groups
        self._compression = compression
        self._sparse_as_dense = sparse_as_dense

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def __enter__(self):
        self._tape.__enter__()
        return self

    def __exit__(self, *exc):
        return self._tape.__exit__(*exc)

    def gradient(self, target, sources, output_gradients=None):
        grads = self._tape.gradient(target, sources, output_gradients)
        if not _spmd():
            return grads
        ngroups, group_ids = _resolve_groups(
            list(sources), self._num_groups, self._groups)
        return _reduce_grads(grads, self._op, self._process_set,
                             self._predivide, ngroups, group_ids,
                             compression=self._compression,
                             sparse_as_dense=self._sparse_as_dense)


def _grouping(n, num_groups, group_ids):
    """Split n gradient slots into fusion buckets (reference:
    horovod/tensorflow/__init__.py:627+ honors num_groups; groups= maps
    variables to explicit buckets). Returns a list of index lists."""
    if group_ids is not None:
        by_gid = {}
        rest = []
        for i in range(n):
            gid = group_ids[i]
            if gid is None:
                rest.append([i])
            else:
                by_gid.setdefault(gid, []).append(i)
        return list(by_gid.values()) + rest
    if num_groups and num_groups > 0:
        return _c.fusion_buckets(n, num_groups)
    return [list(range(n))]


def _sparse_allreduce_tf(slices, op, name, process_set):
    """IndexedSlices through the sparse plane (``sparse_as_dense=False``;
    ops/sparse.py, docs/sparse.md): the ``HVDTPU_SPARSE`` policy picks
    allgather-of-slices vs densify-then-allreduce per tensor (with the
    knob unset every call densifies — the pre-plane path, bit-identical).
    Returns the DENSE reduced tensor: the transport is sparse, the
    result is what apply_gradients consumes either way."""
    tf = _tf()
    from ..ops import sparse as sparse_ops

    def fn(arrs):
        idx, vals, shp = arrs
        sg = sparse_ops.SparseGradient(
            np.asarray(idx, np.int64), np.asarray(vals),
            [int(s) for s in np.asarray(shp)])
        out = sparse_ops.sparse_allreduce(sg, op=op, name=name,
                                          process_set=process_set)
        return [_result_np(out)]

    # dense_shape rides as an input so graph mode resolves it at
    # execution time like the data tensors (py_function boundary).
    out = _eager(fn, [slices.indices, slices.values,
                      tf.cast(slices.dense_shape, tf.int64)],
                 [slices.values.dtype], name)[0]
    static = tf.get_static_value(tf.convert_to_tensor(
        slices.dense_shape))
    if static is not None:
        out = tf.ensure_shape(out, [int(s) for s in static])
    return out


def _reduce_grads(grads, op, process_set, predivide=1.0, num_groups=0,
                  group_ids=None, compression=None,
                  sparse_as_dense=True):
    tf = _tf()
    dense_idx, dense = [], []
    result = list(grads)
    for i, g in enumerate(grads):
        if g is None:
            continue
        if isinstance(g, tf.IndexedSlices):
            if not sparse_as_dense:
                # The honored sparse_as_dense=False contract: the
                # slices ride the sparse plane (per-tensor gather vs
                # densify policy) instead of the unconditional
                # densification below. Sum/Average only — other ops
                # reject loudly inside sparse_allreduce.
                result[i] = _sparse_allreduce_tf(
                    g, op, f"grad_reduce.sp{i}", process_set)
                continue
            g = tf.convert_to_tensor(g)
        dense_idx.append(i)
        dense.append(g)
    if not dense:
        return result
    pre = 1.0 / predivide if predivide != 1.0 else 1.0
    post = predivide / 1.0 if predivide != 1.0 else 1.0
    sub_ids = None if group_ids is None else \
        [group_ids[i] for i in dense_idx]
    for b, bucket in enumerate(_grouping(len(dense), num_groups, sub_ids)):
        outs = grouped_allreduce([dense[j] for j in bucket], op=op,
                                 prescale_factor=pre, postscale_factor=post,
                                 name=f"grad_reduce.g{b}",
                                 compression=compression,
                                 process_set=process_set)
        for j, o in zip(bucket, outs):
            result[dense_idx[j]] = o
    return result


def tpu_compile(fn, example_inputs=None, input_signature=None,
                dynamic_batch=True):
    """Compile a TF2 callable to a jitted JAX function so the model math
    runs on the TPU (see horovod_tpu/tensorflow/compile.py — the graph→JAX
    redesign of the reference's device-kernel registration,
    horovod/tensorflow/mpi_ops.cc:486-493 / xla_mpi_ops.cc:174-232)."""
    from .compile import tpu_compile as _impl
    return _impl(fn, example_inputs=example_inputs,
                 input_signature=input_signature,
                 dynamic_batch=dynamic_batch)


def _resolve_groups(tvars, num_groups, groups):
    """Normalize the reference's two grouping spellings (reference:
    horovod/tensorflow/__init__.py:627+): ``num_groups`` (int bucket
    count) or ``groups`` (int, or list of lists of variables). Returns
    (num_groups, group_ids) where group_ids maps each grad slot to a
    bucket id (None = ungrouped)."""
    if groups is None:
        return num_groups, None
    if isinstance(groups, int):
        return groups, None
    by_ref = {}
    for gid, bucket in enumerate(groups):
        for v in bucket:
            by_ref[v.ref()] = gid
    return 0, [by_ref.get(v.ref()) for v in tvars]


def DistributedOptimizer(optimizer, name=None, use_locking=False,
                         device_dense="", device_sparse="",
                         compression=None, sparse_as_dense=True,
                         backward_passes_per_step=1, op=Average,
                         gradient_predivide_factor=1.0,
                         average_aggregated_gradients=True,
                         num_groups=0, groups=None,
                         process_set=global_process_set):
    """Wrap a tf.keras optimizer so apply_gradients() averages gradients
    across ranks first, with optional local aggregation over
    ``backward_passes_per_step`` (reference:
    horovod/tensorflow/__init__.py:627).

    Aggregation is graph-state based — a tf.Variable counter and
    accumulator slots driven by tf.cond — so it is exact inside
    ``tf.function`` train steps, where a Python-side counter would
    freeze at its trace-time value (reference design:
    horovod/tensorflow/gradient_aggregation.py:16). The rank-sync and
    the inner apply happen only on every k-th call; skip calls just
    accumulate. ``num_groups``/``groups`` bound the gradient fusion
    buckets like the reference. ``compression`` (Compression.fp16/bf16)
    shrinks the bytes the host data plane carries per sync.
    ``device_dense``/``device_sparse`` are GPU stream placement in the
    reference — inert here (XLA owns device placement).
    ``sparse_as_dense=False`` routes IndexedSlices gradients through
    the sparse plane (ops/sparse.py): the ``HVDTPU_SPARSE`` policy
    picks allgather-of-slices vs densify per tensor, and the reduced
    gradient comes back dense; True (default) densifies before the
    sync, the reference's sparse_as_dense=True behavior."""
    k = int(backward_passes_per_step)
    if k < 1:
        raise ValueError("backward_passes_per_step must be >= 1")
    if k > 1 and op == Adasum:
        raise ValueError(
            "backward_passes_per_step > 1 with Adasum is unsupported "
            "(nonlinear reduction); aggregate in the training loop.")
    cls = type(optimizer)
    log = get_logger()

    class _Distributed(cls):
        _hvd_wrapped = True

        def __init__(self):  # pragma: no cover — state is copied below
            pass

        def _hvd_ensure_state(self, tf, grads):
            if self._hvd_counter is not None:
                return
            # init_scope lifts creation out of tf.function tracing, so
            # the variables are created exactly once (first trace) and
            # persist across calls — the reference's graph-state design.
            with tf.init_scope():
                self._hvd_counter = tf.Variable(
                    0, trainable=False, dtype=tf.int64,
                    name="hvd_agg_counter")
                self._hvd_acc = [
                    None if g is None else tf.Variable(
                        tf.zeros(g.shape, g.dtype), trainable=False,
                        name=f"hvd_agg_{i}")
                    for i, g in enumerate(grads)]

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            tf = _tf()
            gv = list(grads_and_vars)
            grads = [g for g, _ in gv]
            tvars = [v for _, v in gv]
            ngroups, group_ids = _resolve_groups(tvars, num_groups,
                                                 groups)

            def reduce_and_apply(grads):
                if _spmd():
                    # _reduce_grads densifies IndexedSlices only here, on
                    # the sync path — single-rank sparse gradients keep
                    # the inner optimizer's sparse application. With
                    # sparse_as_dense=False they ride the sparse plane.
                    grads = _reduce_grads(grads, op, process_set,
                                          gradient_predivide_factor,
                                          ngroups, group_ids,
                                          compression=compression,
                                          sparse_as_dense=sparse_as_dense)
                return cls.apply_gradients(self, list(zip(grads, tvars)),
                                           *args, **kwargs)

            if k == 1:
                return reduce_and_apply(grads)

            # Accumulator slots are dense: aggregation materializes
            # sparse gradients by construction.
            grads = [None if g is None
                     else tf.convert_to_tensor(g) if isinstance(
                         g, tf.IndexedSlices) else g
                     for g in grads]
            self._hvd_ensure_state(tf, grads)
            if len(grads) != len(self._hvd_acc):
                raise ValueError(
                    f"backward_passes_per_step aggregation was built for "
                    f"{len(self._hvd_acc)} gradients but this "
                    f"apply_gradients call passed {len(grads)}; the "
                    "variable list must stay fixed across calls.")
            self._hvd_counter.assign_add(1)
            for acc, g in zip(self._hvd_acc, grads):
                if g is not None:
                    acc.assign_add(g)
            do_step = tf.equal(self._hvd_counter % k, 0)

            def _apply():
                agg = [None if acc is None else
                       (acc.read_value() / k if average_aggregated_gradients
                        else acc.read_value())
                       for acc in self._hvd_acc]
                reduce_and_apply(agg)
                for acc in self._hvd_acc:
                    if acc is not None:
                        acc.assign(tf.zeros_like(acc))
                return tf.constant(True)

            def _skip():
                return tf.constant(False)

            return tf.cond(do_step, _apply, _skip)

    # Rebrand the instance in place (the reference builds a dynamic
    # subclass the same way, horovod/_keras/__init__.py:36).
    opt = optimizer
    opt.__class__ = _Distributed
    opt._hvd_counter = None
    opt._hvd_acc = None
    if _spmd():
        log.info("tensorflow DistributedOptimizer wrapping %s over %d "
                 "ranks", cls.__name__, size())
    return opt


def __getattr__(name):
    # SyncBatchNormalization lives in its own module and subclasses a
    # keras Layer; resolve it lazily so importing the binding never
    # imports tensorflow/keras (cached in globals for identity).
    if name == "SyncBatchNormalization":
        from .sync_batch_norm import SyncBatchNormalization
        globals()[name] = SyncBatchNormalization
        return SyncBatchNormalization
    raise AttributeError(name)
