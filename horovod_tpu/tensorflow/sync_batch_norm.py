"""Cross-rank synchronized batch normalization for TensorFlow
(reference: horovod/tensorflow/sync_batch_norm.py:151
``SyncBatchNormalization``).

Self-contained Keras layer (no tf.keras BatchNormalization internals —
those changed across Keras versions): global-batch statistics via a
py_function-bridged allreduce in the forward pass, and the chain rule's
sum_dy / sum_dy_xmu allreduced inside a ``tf.custom_gradient`` backward,
mirroring the torch SyncBatchNorm in this repo.
"""

import numpy as np

from . import _spmd
from ..ops import collectives as _c
from ..ops import reduce_ops
from ..process_sets import global_process_set


def _tf():
    import tensorflow as tf
    return tf


def _allreduce_sum_np(arr, name):
    """Blocking sum-allreduce on a numpy array (py_function body)."""
    return np.asarray(_c.allreduce(arr, op=reduce_ops.Sum, name=name,
                                   process_set=global_process_set))


def _py_allreduce(tensor, name):
    tf = _tf()

    def fn(t):
        return tf.convert_to_tensor(_allreduce_sum_np(t.numpy(), name))

    out = tf.py_function(func=fn, inp=[tensor], Tout=tensor.dtype)
    out.set_shape(tensor.shape)
    return out


def SyncBatchNormalization(axis=-1, momentum=0.99, epsilon=1e-3,
                           center=True, scale=True, name=None, **kwargs):
    """Build the layer (function wrapper so importing this module never
    imports tensorflow; reference exposes a class — the returned object
    behaves identically)."""
    tf = _tf()

    class _SyncBatchNormalization(tf.keras.layers.Layer):
        def __init__(self):
            super().__init__(name=name, **kwargs)
            self.axis = axis
            self.momentum = momentum
            self.epsilon = epsilon
            self.center = center
            self.scale = scale

        def build(self, input_shape):
            dim = int(input_shape[self.axis])
            self.gamma = self.add_weight(
                name="gamma", shape=(dim,), initializer="ones",
                trainable=self.scale)
            self.beta = self.add_weight(
                name="beta", shape=(dim,), initializer="zeros",
                trainable=self.center)
            self.moving_mean = self.add_weight(
                name="moving_mean", shape=(dim,), initializer="zeros",
                trainable=False)
            self.moving_variance = self.add_weight(
                name="moving_variance", shape=(dim,), initializer="ones",
                trainable=False)
            super().build(input_shape)

        def _broadcast_shape(self, x):
            shape = [1] * len(x.shape)
            shape[self.axis] = x.shape[self.axis]
            return shape

        def call(self, inputs, training=False):
            x = inputs
            bshape = self._broadcast_shape(x)
            if not training or not _spmd():
                inv = tf.math.rsqrt(self.moving_variance + self.epsilon)
                out = (x - tf.reshape(self.moving_mean, bshape)) \
                    * tf.reshape(inv, bshape)
                return out * tf.reshape(self.gamma, bshape) \
                    + tf.reshape(self.beta, bshape)

            ndims = len(x.shape)
            ax = self.axis % ndims
            reduce_axes = [d for d in range(ndims) if d != ax]
            c = x.shape[ax]

            local_count = tf.cast(
                tf.size(x) / c, x.dtype)
            local_sum = tf.reduce_sum(x, axis=reduce_axes)
            local_sqsum = tf.reduce_sum(x * x, axis=reduce_axes)
            packed = tf.concat(
                [local_sum, local_sqsum, tf.reshape(local_count, (1,))],
                axis=0)
            packed = _py_allreduce(packed, f"tf_syncbn.fwd.{c}")
            total = packed[-1]
            mean = packed[:c] / total
            var = packed[c:2 * c] / total - mean * mean
            invstd = tf.math.rsqrt(var + self.epsilon)

            # Running stats (unbiased variance, reference semantics).
            unbiased = var * (total / tf.maximum(total - 1.0, 1.0))
            self.moving_mean.assign(
                self.moving_mean * self.momentum
                + mean * (1.0 - self.momentum))
            self.moving_variance.assign(
                self.moving_variance * self.momentum
                + unbiased * (1.0 - self.momentum))

            # Convert to tensors BEFORE the custom_gradient boundary:
            # captured tf.Variables would force the grad_fn to accept a
            # `variables` kwarg; with tensors the Variable->tensor read is
            # on the tape and dgamma/dbeta flow through normally.
            gamma = tf.convert_to_tensor(self.gamma)
            beta = tf.convert_to_tensor(self.beta)

            @tf.custom_gradient
            def _normalize(xin, g, b):
                xmu = xin - tf.reshape(mean, bshape)
                xhat = xmu * tf.reshape(invstd, bshape)
                out = xhat * tf.reshape(g, bshape) \
                    + tf.reshape(b, bshape)

                def grad(dy):
                    sum_dy = tf.reduce_sum(dy, axis=reduce_axes)
                    sum_dy_xmu = tf.reduce_sum(dy * xmu, axis=reduce_axes)
                    packed_g = tf.concat([sum_dy, sum_dy_xmu], axis=0)
                    packed_g = _py_allreduce(packed_g,
                                             f"tf_syncbn.bwd.{c}")
                    g_sum_dy = packed_g[:c]
                    g_sum_dy_xmu = packed_g[c:]
                    inv = tf.reshape(invstd, bshape)
                    dx = (dy
                          - tf.reshape(g_sum_dy, bshape) / total
                          - xmu * inv * inv
                          * tf.reshape(g_sum_dy_xmu, bshape) / total) \
                        * inv * tf.reshape(g, bshape)
                    dgamma = tf.reduce_sum(dy * xhat, axis=reduce_axes)
                    dbeta = sum_dy
                    return dx, dgamma, dbeta

                return out, grad

            return _normalize(x, gamma, beta)

    return _SyncBatchNormalization()
