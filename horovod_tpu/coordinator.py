"""Background coordinator: tensor queue, fusion, dispatch, async handles.

TPU rethink of the reference's background thread + controller
(reference: horovod/common/operations.cc:385 BackgroundThreadLoop,
:706 RunLoopOnce; horovod/common/controller.cc:73 ComputeResponseList):

- Framework threads **submit** named tensors into a queue and get a handle
  back immediately (reference: EnqueueTensorAllreduces,
  horovod/common/operations.cc:1384).
- A single background thread drains the queue every cycle (default 1 ms,
  reference: operations.cc:499), groups compatible requests, **fuses** each
  group by concatenating flattened tensors into one buffer per dtype
  (reference fusion: controller.cc:808 FuseResponses + 128 MiB threshold,
  operations.cc:491), and dispatches ONE backend collective per buffer.
- In single-controller mode no negotiation is needed — this process owns
  every virtual rank, so readiness is immediate and the controller's
  response-cache fast path (reference: response_cache.cc) degenerates to the
  backend's compiled-program cache. In SPMD mode the native controller
  negotiates readiness across processes before dispatch (backend handles it).
"""

import threading
import time

import numpy as np

from . import chaos
from .analysis import sanitizer
from .exceptions import DuplicateNameError, HorovodInternalError
from .ops import reduce_ops
from .telemetry import span as tele_span
from .telemetry import core as telemetry
from .utils import envparse
from .utils.callsite import format_user_frame
from .utils.logging_util import get_logger

DEFAULT_CYCLE_TIME_MS = 1.0
DEFAULT_FUSION_THRESHOLD = 128 * 1024 * 1024  # reference: operations.cc:491
# Warn when a submitted op stays in flight this long (reference stall
# inspector default, horovod/common/stall_inspector.cc).
DEFAULT_STALL_WARN_S = 60.0
# Seconds between SPMD submission-order cross-checks (ORDER_CHECK mode).
DEFAULT_ORDER_CHECK_INTERVAL_S = 5.0
# Fused element counts are rounded to a multiple of this so bucket boundaries
# stay aligned for XLA tiling (reference: FUSION_BUFFER_ATOMIC_UNIT=64,
# horovod/common/common.h:147).
FUSION_ATOMIC_UNIT = 64


class Handle:
    """Async completion handle (analog of the reference's int handle +
    handle_manager, reference: horovod/torch/mpi_ops_v2.cc:604-624)."""

    __slots__ = ("_event", "_result", "_exception", "name",
                 "enqueue_time", "_coord")

    def __init__(self, name):
        self._event = threading.Event()
        self._result = None
        self._exception = None
        self.name = name
        self.enqueue_time = None   # stamped by TensorEntry
        self._coord = None         # stamped by Coordinator.submit

    def _complete(self, result):
        self._result = result
        self._event.set()

    def _fail(self, exc):
        self._exception = exc
        self._event.set()

    def poll(self):
        """True when the operation completed (reference: PollHandle,
        horovod/torch/mpi_ops_v2.cc:604)."""
        return self._event.is_set()

    def wait(self, timeout=None):
        # hvd-sanitize tripwire: a wait on the cycle/watchdog thread
        # would starve every other in-flight collective (no-op + one
        # global read when HVDTPU_SANITIZE is off).
        sanitizer.check_blocking("Handle.wait", self.name or "")
        if not self._event.wait(timeout):
            age = ("" if self.enqueue_time is None else
                   f"; in flight {time.monotonic() - self.enqueue_time:.1f}s"
                   " since submit")
            hint = ("" if self._coord is None
                    else self._coord._describe_missing(self.name))
            raise TimeoutError(
                f"Operation {self.name!r} did not complete within "
                f"{timeout}s{age}{hint}")
        if self._exception is not None:
            raise self._exception
        return self._result


class TensorEntry:
    __slots__ = ("name", "kind", "op", "root_rank", "arrays", "splits",
                 "prescale", "postscale", "process_set", "handle",
                 "enqueue_time", "shapes", "uneven", "guard_token",
                 "chaos_mismatch", "codec", "corr", "sparse")

    def __init__(self, name, kind, arrays, process_set, op=None,
                 root_rank=None, splits=None, prescale=None, postscale=None,
                 uneven=False, codec=None):
        self.name = name
        self.kind = kind
        self.arrays = arrays
        self.process_set = process_set
        self.op = op
        self.root_rank = root_rank
        self.splits = splits
        self.prescale = prescale
        self.postscale = postscale
        self.uneven = uneven
        self.handle = Handle(name)
        self.enqueue_time = time.monotonic()
        self.handle.enqueue_time = self.enqueue_time
        # Armed by guardian.ConsistencyGuard.on_submit when this entry's
        # submission slot is sampled for a pre-dispatch digest check.
        self.guard_token = None
        # Chaos 'collective:mismatch': publish a corrupted digest.
        self.chaos_mismatch = False
        # Compression: a codec-name string at submit (explicit
        # Compression.int8-style marker), resolved by the plane's
        # stamp() into the (name, block) tuple the fusion plane groups
        # by and the guardian digests; None = uncompressed.
        self.codec = codec
        # Tracing correlation: this name's occurrence number, stamped by
        # tracing.Tracer.on_submit (identical across ranks for a correct
        # program); None when the trace plane is off.
        self.corr = None
        # Sparse gradient plane (ops/sparse.py): a SparseMeta for
        # kind == "sparse_allreduce" entries (dense_shape/index_dtype/
        # nranks/codec); None on every dense entry — the digest and
        # dispatch planes key off it.
        self.sparse = None


def _nbytes(a):
    return int(np.prod(a.shape)) * a.dtype.itemsize


class Coordinator:
    def __init__(self, runtime):
        self.runtime = runtime
        self.cycle_time_s = envparse.get_float(
            envparse.CYCLE_TIME, DEFAULT_CYCLE_TIME_MS) / 1000.0
        self.fusion_threshold = envparse.get_int(
            envparse.FUSION_THRESHOLD, DEFAULT_FUSION_THRESHOLD)
        self._queue = []
        # (process_set_id, name) -> [enqueue_time, callsite|None]
        # for every in-flight named op: duplicate detection + the stall
        # warning scan (reference: tensor_queue + stall_inspector).
        self._pending_names = {}
        # Chaos 'collective:stall' black hole: entries swallowed at
        # submit time (this rank "never submitted" them). Invisible to
        # the data plane AND the published in-flight view, but aged by
        # the watchdog so their handles fail at the abort instead of
        # blocking a waiter forever.
        self._chaos_stalled = []
        # Instrumented under HVDTPU_SANITIZE (lock-order graph +
        # blocking tripwire); the plain primitive otherwise.
        self._lock = sanitizer.make_lock("coordinator.queue")
        self._wakeup = threading.Event()
        self._running = False
        self._thread = None
        self._log = get_logger()
        # Stats consumed by the autotuner / timeline.
        self.cycles = 0
        self.bytes_processed = 0
        self.tensors_processed = 0
        # Stall warning (HOROVOD_TPU_STALL_CHECK_TIME, legacy spelling
        # STALL_CHECK_TIME_SECONDS; 0 / STALL_CHECK_DISABLE turns it off).
        if envparse.get_bool(envparse.STALL_CHECK_DISABLE):
            self.stall_warn_s = 0.0
        else:
            self.stall_warn_s = envparse.get_float(
                envparse.STALL_CHECK_TIME, envparse.get_float(
                    envparse.STALL_CHECK_TIME_SECONDS,
                    DEFAULT_STALL_WARN_S))
        # Data-plane guardian (guardian.py; docs/fault_tolerance.md).
        # Both None when their knobs are unset: the hot paths pay one
        # attribute check and nothing else.
        from . import guardian
        self._guardian = guardian.make_guard(runtime)
        self._watchdog = guardian.make_watchdog(runtime)
        # Gradient-compression plane (compression/; docs/compression.md).
        # None when HVDTPU_COMPRESSION is unset: the submit path pays
        # two None checks and nothing else. Lazily created when an
        # explicit per-call codec marker (Compression.int8) arrives
        # with the env unset.
        from . import compression as compression_mod
        self._compression = compression_mod.make_plane(runtime)
        # Sparse/embedding gradient plane (ops/sparse.py;
        # docs/sparse.md). None when HVDTPU_SPARSE is unset: the dense
        # hot path never sees a sparse entry (sparse_allreduce then
        # densifies at the user layer into TODAY's allreduce path) and
        # no per-name EMA state exists — guard-tested.
        from .ops import sparse as sparse_mod
        self._sparse = sparse_mod.make_plane()
        # Cross-rank trace plane (tracing/; docs/tracing.md). None when
        # HVDTPU_TRACE is off AND the flight recorder is disabled: the
        # submit/complete paths pay one attribute check. With only the
        # (default-on) flight recorder, each event is a bounded deque
        # append — no file I/O, no KV traffic.
        from . import tracing
        self._tracer = tracing.make_tracer(runtime)
        runtime.tracer = self._tracer
        self._stall_scan_period = (max(1.0, min(self.stall_warn_s / 2.0,
                                                10.0))
                                   if self.stall_warn_s > 0 else 10.0)
        if self._watchdog is not None:
            # Scans must be frequent enough to notice the abort timeout.
            self._stall_scan_period = max(0.25, min(
                self._stall_scan_period, self._watchdog.timeout_s / 4.0))
        # Age past which an op counts as stalled for the scan: the warn
        # threshold, tightened to half the abort timeout when the
        # watchdog's deadline is shorter than the warning's.
        self._stall_observe_s = (self.stall_warn_s
                                 if self.stall_warn_s > 0
                                 else float("inf"))
        if self._watchdog is not None:
            self._stall_observe_s = min(self._stall_observe_s,
                                        self._watchdog.timeout_s / 2.0)
        self._last_stall_scan = time.monotonic()
        self._stall_logged = set()
        self._stall_last_log = -float("inf")
        self._m_aborts = telemetry.counter(
            "hvd_collective_abort_total",
            "Coordinated watchdog aborts of in-flight collectives")
        # Metrics plane (telemetry/): with HOROVOD_TPU_METRICS off every
        # factory returns the shared NULL no-op, so the hot paths below
        # stay unconditional; arithmetic-only sites additionally gate on
        # the bool to skip clock reads and byte counting.
        self._metrics_on = telemetry.enabled()
        # Chaos 'collective' point (HVDTPU_CHAOS): cached like the
        # metrics flag so the default submit path pays one bool check.
        self._chaos_on = chaos.enabled()
        self._m_cycle_s = telemetry.histogram(
            "hvd_coordinator_cycle_seconds",
            "Duration of coordinator cycles that moved tensors")
        self._m_queue_depth = telemetry.gauge(
            "hvd_coordinator_queue_depth",
            "Entries drained from the submission queue by the last cycle")
        self._m_queue_wait_s = telemetry.histogram(
            "hvd_coordinator_queue_wait_seconds",
            "Time an entry waited between submit() and dispatch")
        self._m_dispatch_s = telemetry.histogram(
            "hvd_coordinator_dispatch_seconds",
            "Backend dispatch latency per operation (span duration)",
            labelnames=("kind",))
        self._m_ops = telemetry.counter(
            "hvd_coordinator_ops_total",
            "Operations dispatched to the backend", labelnames=("kind",))
        self._m_fused_bytes = telemetry.counter(
            "hvd_coordinator_fused_bytes_total",
            "Payload bytes through the fusion plane",
            labelnames=("dtype",))
        self._m_fusion_payload = telemetry.counter(
            "hvd_coordinator_fusion_payload_bytes_total",
            "Fused payload bytes (excluding atomic-unit padding)")
        self._m_fusion_padding = telemetry.counter(
            "hvd_coordinator_fusion_padding_bytes_total",
            "Bytes of atomic-unit padding the fusion plane would add")
        self._m_fusion_eff = telemetry.gauge(
            "hvd_coordinator_fusion_efficiency",
            "payload / (payload + padding) of the last fused buffer")
        # Bucketed comm/compute overlap (HVDTPU_OVERLAP;
        # docs/performance.md): the eager plane issues fusion buckets
        # asynchronously in priority (submission) order, then completes
        # them — instead of one blocking dispatch per bucket.
        from .ops.bucketing import DEFAULT_BUCKET_BYTES
        self._overlap = envparse.get_bool(envparse.OVERLAP)
        self._bucket_bytes = envparse.get_int(
            envparse.BUCKET_BYTES, DEFAULT_BUCKET_BYTES)
        self._m_overlap_fraction = telemetry.gauge(
            "hvd_overlap_fraction",
            "Share of the last cycle's collective in-flight time hidden "
            "under other work (issue/prep of later buckets) rather than "
            "blocking the cycle thread")
        self._m_overlap_hidden_s = telemetry.histogram(
            "hvd_overlap_hidden_seconds",
            "Per-bucket collective time hidden under later dispatches")
        self._m_stalled = telemetry.gauge(
            "hvd_coordinator_stalled_ops",
            "In-flight operations older than the stall threshold")
        self._m_stalled_oldest = telemetry.gauge(
            "hvd_coordinator_stalled_oldest_age_seconds",
            "Age of the oldest stalled operation")
        # Opt-in submission-order guard (HOROVOD_TPU_ORDER_CHECK=1).
        # None when disabled: the hot path pays one attribute check and
        # allocates nothing (see analysis/order_guard.py).
        self._order_guard = None
        self._order_error = None
        self._order_thread = None
        self._order_record_path = None
        if envparse.get_bool(envparse.ORDER_CHECK):
            from .analysis.order_guard import SubmissionOrderGuard
            self._order_record_path = (
                envparse.get_str(envparse.ORDER_CHECK_RECORD) or None)
            spmd = getattr(runtime, "mode", None) == "spmd"
            self._order_guard = SubmissionOrderGuard(
                rank=runtime.topology.rank,
                record=(not spmd) or bool(self._order_record_path))

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        # start() and stop() may run on different threads (elastic
        # reset vs. user shutdown). Two hazards closed here: (a) an
        # unguarded read-then-set of _running reviving a coordinator
        # mid-teardown, so the flag flips under the lock like stop()'s
        # does; (b) a start() racing a just-issued stop() re-raising
        # _running before the OLD cycle thread observed False — it
        # would then never exit and TWO cycle threads would dispatch
        # concurrently. So the previous thread is drained first. The
        # new thread is created AND started inside the critical
        # section so a concurrent stop() never joins a stale or
        # not-yet-started thread object; the cycle thread only touches
        # self._lock from its loop body, so holding the lock across
        # start() cannot deadlock.
        with self._lock:
            if self._running:
                return
            prev = self._thread
        if prev is not None:
            prev.join(timeout=10)
        with self._lock:
            if self._running:
                return
            self._running = True
            self._thread = threading.Thread(
                target=self._loop, name="hvd-tpu-coordinator",
                daemon=True)
            self._thread.start()
        if (self._order_guard is not None
                and getattr(self.runtime, "mode", None) == "spmd"
                and self.runtime.topology.size > 1):
            self._order_thread = threading.Thread(
                target=self._order_check_loop,
                name="hvd-tpu-order-check", daemon=True)
            self._order_thread.start()

    def stop(self):
        with self._lock:
            if not self._running:
                return
            # Flip under the lock so no submit() can slip into a queue that
            # will never be serviced.
            self._running = False
        self._wakeup.set()
        self._thread.join(timeout=10)
        if self._order_thread is not None:
            self._order_thread.join(timeout=10)
        if (self._order_guard is not None
                and self._order_record_path is not None):
            try:
                path = self._order_guard.dump(self._order_record_path)
                self._log.info("submission-order record written to %s",
                               path)
            except OSError as exc:
                self._log.warning("could not write ORDER_CHECK record: %s",
                                  exc)
        with self._lock:
            stranded = self._queue + self._chaos_stalled
            self._queue = []
            self._chaos_stalled = []
            self._pending_names.clear()
        for e in stranded:
            e.handle._fail(HorovodInternalError(
                "Coordinator shut down with operations in flight"))

    # -- submission (framework-thread side) --------------------------------
    def submit(self, entry):
        if self._chaos_on:
            # Raises HorovodInternalError on a matching fail rule — the
            # same exception a real collective failure surfaces, so the
            # elastic restore path is exercised end to end. Signal
            # actions (stall/mismatch) are applied here instead.
            try:
                chaos.inject("collective", name=entry.name,
                             kind=entry.kind)
            except chaos.ChaosSignal as sig:
                if sig.action == "stall":
                    return self._chaos_swallow(entry)
                if sig.action == "mismatch":
                    entry.chaos_mismatch = True
        if entry.kind == "allreduce" and (self._compression is not None
                                          or entry.codec is not None):
            if self._compression is None:
                # Explicit Compression.int8-style marker with the env
                # policy unset: build a plane on demand (default policy,
                # residual store, metrics).
                from . import compression as compression_mod
                self._compression = compression_mod.make_plane(
                    self.runtime, force=True)
                backend = self.runtime.backend
                if getattr(backend, "drives_own_cycle", False):
                    # The native loop handed its (then-None) plane ref
                    # to the backend at start — refresh it.
                    backend.compression_plane = self._compression
            # Stamp BEFORE the guardian digest so every rank's digest
            # carries the selected codec (a codec mismatch fails fast
            # as CollectiveMismatchError instead of corrupting bytes).
            # Raises the loud Adasum / process-set rejects here, on the
            # submitting thread.
            self._compression.stamp(entry)
        if self._guardian is not None:
            # Publish the digest BEFORE the entry can reach a dispatch
            # cycle, so a peer's verify never races an unpublished
            # digest from this rank. May touch the KV board: outside
            # the queue lock by design.
            self._guardian.on_submit(entry)
        if self._tracer is not None:
            # Stamp the correlation key (name x occurrence x elastic
            # version) and record the submission instant — the cross-
            # rank merge joins every rank's span on this key.
            self._tracer.on_submit(entry)
        key = (entry.process_set.process_set_id, entry.name)
        guard = self._order_guard
        # Call-site capture only in ORDER_CHECK mode: the default hot
        # path stays a dict insert.
        site = format_user_frame() if guard is not None else None
        with self._lock:
            if not self._running:
                raise HorovodInternalError(
                    "Coordinator is shut down; cannot submit operations")
            if guard is not None and self._order_error is not None:
                raise self._order_error
            if entry.name and key in self._pending_names:
                raise self._duplicate_error(entry, key)
            if entry.name:
                self._pending_names[key] = [entry.enqueue_time, site]
            self._queue.append(entry)
            if (guard is not None and entry.name
                    and not entry.name.startswith("hvdlint.")):
                # Inside the lock so the digest stream mirrors the true
                # queue order even with concurrent submitter threads.
                # Guard-internal ops ("hvdlint.*") are excluded: the
                # checker submits on a timer, so they would land at
                # rank-dependent stream positions and poison the digest.
                guard.record(entry.name, entry.kind, callsite=site)
        entry.handle._coord = self
        self._wakeup.set()
        return entry.handle

    def _chaos_swallow(self, entry):
        """Chaos 'collective:stall': this rank never submits the op —
        peers stall on it and the watchdog gets to prove it can name
        this rank and abort. The entry parks in the black hole so the
        abort (or shutdown) still resolves its waiter."""
        with self._lock:
            if not self._running:
                raise HorovodInternalError(
                    "Coordinator is shut down; cannot submit operations")
            self._chaos_stalled.append(entry)
        entry.handle._coord = self
        self._log.warning(
            "chaos: collective %r swallowed (stall injection) — this "
            "rank will never submit it", entry.name)
        return entry.handle

    def _duplicate_error(self, entry, key):
        first = self._pending_names[key]
        first_site = first[1] or (
            "<unknown; set HOROVOD_TPU_ORDER_CHECK=1 to record "
            "submission call-sites>")
        return DuplicateNameError(
            f"Duplicate tensor name {entry.name!r} in flight for "
            f"process set {entry.process_set.process_set_id}: first "
            f"submitted at {first_site}, duplicate submitted at "
            f"{format_user_frame()}. Names must be unique among "
            "in-flight operations (reference: "
            "horovod/common/tensor_queue.cc). If the name is "
            "auto-generated, rank-divergent call orders are the usual "
            "cause — see hvd-lint rule HVD203 (docs/lint.md).")

    def _release_name(self, entry):
        if entry.name:
            with self._lock:
                self._pending_names.pop(
                    (entry.process_set.process_set_id, entry.name), None)

    def _entry_done(self, entry, ok=True):
        """Native-plane completion callback (tcp/xla-global backends):
        release the name and record the trace completion. Failure
        paths pass ``ok=False`` so merged traces and postmortems flag
        the failing collective instead of drawing a clean span."""
        self._release_name(entry)
        if self._tracer is not None:
            self._tracer.on_complete(entry, ok=ok)

    # -- background cycle --------------------------------------------------
    def _loop(self):
        # The cycle thread paces the whole data plane (and runs the
        # watchdog scans from _check_stalls), so any blocking call on
        # it is a finding for the sanitize tripwire. Unmarked on exit:
        # thread idents are recycled, and a stale entry would smear
        # "collective-critical" onto an unrelated later thread across
        # elastic stop/start cycles.
        sanitizer.mark_critical("coordinator-cycle")
        try:
            backend = self.runtime.backend
            if getattr(backend, "drives_own_cycle", False):
                self._loop_native(backend)
                return
            while self._running:
                self._wakeup.wait(timeout=0.25)
                self._wakeup.clear()
                if not self._running:
                    break
                time.sleep(self.cycle_time_s)
                self._run_cycle()
                if self.stall_warn_s > 0 or self._watchdog is not None:
                    self._check_stalls()
        finally:
            sanitizer.unmark_critical()

    def _loop_native(self, backend):
        """SPMD mode: the native core owns negotiation and fusion — local
        grouping decisions would diverge across ranks, so every entry is
        handed to the native controller and the loop just drives cycles
        (the analog of the reference background thread calling RunLoopOnce,
        reference: horovod/common/operations.cc:706). Cycles run even with
        an empty local queue: peers may need this rank for negotiation."""
        backend.entry_done_cb = self._entry_done
        # The pure-TCP plane executes wire-codec entries host-side
        # (quantized allgather + f32 reduce) and threads error-feedback
        # residuals through this plane (None when compression is off).
        backend.compression_plane = self._compression
        # Sparse gather-path entries record their wire accounting
        # through the plane (None when HVDTPU_SPARSE is off).
        backend.sparse_plane = self._sparse
        while self._running:
            time.sleep(self.cycle_time_s)
            with self._lock:
                batch = self._queue
                self._queue = []
            if self._guardian is not None and batch:
                batch = self._verify_consistency(batch)
            for e in batch:
                backend.submit_entry(e)
            self.cycles += 1
            cycle_ts_us = time.perf_counter_ns() // 1000
            # Raw begin/end pair instead of the span API on purpose:
            # only cycles that MOVED tensors may observe (the native
            # loop polls continuously and idle ticks would drown the
            # histogram), and a span observes unconditionally.
            # hvd-lint: disable=HVD207
            t0 = time.perf_counter() if self._metrics_on else 0.0
            processed = backend.run_cycle()
            if self._metrics_on and processed:
                self._m_cycle_s.observe(time.perf_counter() - t0)  # noqa: E501  hvd-lint: disable=HVD207
                self._m_queue_depth.set(len(batch))
            self.tensors_processed += processed
            self.bytes_processed = backend.core.bytes_processed()
            timeline = self.runtime.timeline
            if (processed and timeline is not None
                    and timeline.mark_cycles):
                # Mark only cycles that moved tensors (the native loop
                # polls continuously; idle ticks would flood the trace) —
                # stamped with the PRE-run_cycle time so the instant
                # aligns with the cycle's start like the python plane.
                timeline.marker("CYCLE_START", ts_us=cycle_ts_us)
            if self.runtime.autotuner is not None:
                # Candidate switches are cycle-count driven so every rank
                # applies the same knob at the same negotiation round.
                self.runtime.autotuner.record_cycle()
            if self.stall_warn_s > 0 or self._watchdog is not None:
                self._check_stalls()

    def _check_stalls(self, now=None):
        """Scan for submissions in flight longer than the stall threshold
        — the python-plane analog of the reference's stall inspector
        (horovod/common/stall_inspector.cc), upgraded from a log line
        into a cluster diagnostic-and-abort machine (guardian.Watchdog):

        - Feeds the stalled-op gauges and emits ONE summary warning
          (count + oldest op + age + the ranks that never submitted it)
          per change of the stalled set.
        - With ``HVDTPU_COLLECTIVE_TIMEOUT`` armed, publishes this
          rank's in-flight set, fetches the peers', and past the
          timeout runs a coordinated abort: every in-flight handle
          fails with ``CollectiveAbortError`` carrying the diagnostic
          (under elastic that converts into restore-and-reset instead
          of an eternal hang).

        Scans at most every ``_stall_scan_period`` seconds; a cycle
        with nothing stalled costs one clock read and a compare."""
        if now is None:
            now = time.monotonic()
        if now - self._last_stall_scan < self._stall_scan_period:
            return
        self._last_stall_scan = now
        stalled = []
        with self._lock:
            inflight = [key[1] for key in self._pending_names if key[1]]
            for key, info in self._pending_names.items():
                age = now - info[0]
                if age > self._stall_observe_s:
                    stalled.append((key[1], age, info[1]))
            for e in self._chaos_stalled:
                age = now - e.enqueue_time
                if age > self._stall_observe_s:
                    stalled.append((e.name, age, None))
        wd = self._watchdog
        peer_abort = None
        if wd is not None:
            # Runs on EVERY scan (stalled or not) so this rank's
            # published in-flight view never goes stale under a peer's
            # missing-rank diagnosis; the peer fetch inside only
            # happens when something is stalled here.
            try:
                _, peer_abort = wd.observe(
                    inflight, [(n, a) for n, a, _ in stalled], now)
            except Exception as exc:  # noqa: BLE001 — advisory plane
                self._log.warning("watchdog observation failed: %s", exc)
        if not stalled:
            self._m_stalled.set(0)
            self._m_stalled_oldest.set(0.0)
            self._stall_logged = set()
            return
        stalled.sort(key=lambda item: -item[1])
        oldest_name, oldest_age, oldest_site = stalled[0]
        self._m_stalled.set(len(stalled))
        self._m_stalled_oldest.set(oldest_age)
        if wd is not None:
            if peer_abort is not None or wd.should_abort(oldest_age):
                self._abort_inflight(
                    self._abort_diagnostic(stalled, peer_abort))
                return
        if self.stall_warn_s <= 0:
            return
        # The watchdog may tighten the observation threshold below the
        # warning threshold; warn only about genuinely warn-old ops.
        stalled = [s for s in stalled if s[1] > self.stall_warn_s]
        if not stalled:
            return
        oldest_name, oldest_age, oldest_site = stalled[0]
        current = {name for name, _, _ in stalled}
        if (current == self._stall_logged
                and now - self._stall_last_log < self.stall_warn_s):
            return
        self._stall_logged = current
        self._stall_last_log = now
        missing_note = (wd.describe_missing(oldest_name)
                        if wd is not None else "")
        self._log.warning(
            "%d tensor(s) submitted over %.0f s ago have not completed "
            "— ranks may have diverged (some rank never submitted the "
            "matching op). Oldest: %s (%.0f s%s)%s. Run `hvd-lint` on "
            "the training script to check for rank-dependent "
            "collectives (docs/lint.md); tune via "
            "HOROVOD_TPU_STALL_CHECK_TIME.",
            len(stalled), self.stall_warn_s, oldest_name, oldest_age,
            f", submitted at {oldest_site}" if oldest_site else "",
            missing_note)

    def _abort_diagnostic(self, stalled, peer_abort):
        wd = self._watchdog
        if peer_abort is not None:
            return (f"coordinated abort joined (initiated by a peer): "
                    f"{peer_abort}")
        lines = []
        for name, age, site in stalled:
            note = wd.describe_missing(name) if wd is not None else ""
            at = f", submitted at {site}" if site else ""
            lines.append(f"  {name}: in flight {age:.0f}s{at}{note}")
        return (f"stuck-collective watchdog: {len(stalled)} operation(s) "
                f"exceeded HVDTPU_COLLECTIVE_TIMEOUT="
                f"{wd.timeout_s:.0f}s; aborting all in-flight "
                "collectives:\n" + "\n".join(lines))

    def _abort_inflight(self, diagnostic):
        """Coordinated abort: fail EVERY in-flight handle — queued,
        chaos-swallowed, and anything the backend holds in negotiation
        — with the diagnostic attached, and post the abort notice so
        peers stop waiting too. Under elastic the resulting
        ``CollectiveAbortError`` (a ``HorovodInternalError``) converts
        into a restore-and-reset instead of a job death."""
        from .exceptions import CollectiveAbortError
        exc = CollectiveAbortError(diagnostic)
        self._log.error("%s", diagnostic)
        self._m_aborts.inc()
        if self._tracer is not None:
            # Forensics FIRST: the ring still holds the pre-abort
            # events, and every rank joining the coordinated abort
            # dumps its own — the postmortem bundle is "last N seconds,
            # all ranks" (docs/tracing.md).
            self._tracer.event("guardian", "abort",
                               detail=diagnostic[:400])
            self._tracer.dump_postmortem("collective_abort")
        if self._watchdog is not None:
            try:
                self._watchdog.post_abort(diagnostic)
            except Exception as post_exc:  # noqa: BLE001
                self._log.warning("could not post abort notice: %s",
                                  post_exc)
        with self._lock:
            victims = self._queue + self._chaos_stalled
            self._queue = []
            self._chaos_stalled = []
            self._pending_names.clear()
        try:
            self.runtime.backend.abort_inflight(exc)
        except Exception as backend_exc:  # noqa: BLE001
            self._log.warning("backend abort failed: %s", backend_exc)
        for e in victims:
            e.handle._fail(exc)
        self._m_stalled.set(0)
        self._m_stalled_oldest.set(0.0)
        self._stall_logged = set()

    def _describe_missing(self, name):
        """Watchdog's last known missing-rank note for ``name`` (empty
        without a watchdog) — feeds Handle.wait timeout messages."""
        if self._watchdog is None:
            return ""
        return self._watchdog.describe_missing(name)

    def _verify_consistency(self, batch):
        """Pre-dispatch digest verification (guardian.ConsistencyGuard):
        entries whose submission slot was sampled compare every rank's
        published metadata; a divergence fails ONLY that entry's handle
        with ``CollectiveMismatchError`` — the rest of the batch
        dispatches normally. Board trouble degrades to a warning."""
        from .exceptions import CollectiveMismatchError
        ok = []
        for e in batch:
            if e.guard_token is None:
                ok.append(e)
                continue
            try:
                self._guardian.verify(e)
            except CollectiveMismatchError as exc:
                self._log.error("%s", exc)
                if self._tracer is not None:
                    self._tracer.event("guardian", "mismatch",
                                       coll=e.name,
                                       detail=str(exc)[:400])
                    self._tracer.dump_postmortem("collective_mismatch")
                self._release_name(e)
                e.handle._fail(exc)
                continue
            except Exception as exc:  # noqa: BLE001 — advisory check
                self._log.warning(
                    "guardian: consistency check skipped for %s: %s",
                    e.name, exc)
            ok.append(e)
        return ok

    def _order_check_loop(self):
        """SPMD cross-check of the submission-order digests: allgather
        each rank's recent checkpoint digests through the normal eager
        data plane and compare at a common submission index (see
        analysis/order_guard.py). Runs on its own thread so the blocking
        allgather never stalls the cycle driver."""
        from .exceptions import SubmissionOrderError
        from .ops import collectives
        import jax.numpy as jnp

        interval = envparse.get_float(envparse.ORDER_CHECK_INTERVAL,
                                      DEFAULT_ORDER_CHECK_INTERVAL_S)
        interval = max(0.2, interval)
        round_no = 0
        waited = 0.0
        while self._running:
            time.sleep(0.2)
            waited += 0.2
            if waited < interval or not self._running:
                continue
            waited = 0.0
            round_no += 1
            try:
                payload = jnp.asarray(self._order_guard.sync_payload())
                gathered = collectives.allgather(
                    payload, name=f"hvdlint.order_check.{round_no}")
                self._order_guard.verify(
                    np.asarray(gathered), self.runtime.topology.size)
            except SubmissionOrderError as exc:
                self._order_error = exc
                self._log.error("%s", exc)
                return
            except Exception as exc:  # noqa: BLE001 - advisory check
                if self._running:
                    self._log.debug("order check round skipped: %s", exc)

    def _run_cycle(self):
        with self._lock:
            batch = self._queue
            self._queue = []
        if not batch:
            return
        if self._guardian is not None:
            batch = self._verify_consistency(batch)
            if not batch:
                return
        self._m_queue_depth.set(len(batch))
        self.cycles += 1
        if self.runtime.autotuner is not None:
            self.runtime.autotuner.record_cycle()
        timeline = self.runtime.timeline
        if timeline is not None and timeline.mark_cycles:
            timeline.marker("CYCLE_START")
        backend = self.runtime.backend
        # Group allreduces for fusion, sparse entries for the gather
        # transport; run everything else in order.
        fusible, sparse, others = [], [], []
        for e in batch:
            if e.kind == "allreduce":
                fusible.append(e)
            elif e.kind == "sparse_allreduce":
                sparse.append(e)
            else:
                others.append(e)
        # Cycle timing through the span API (rule HVD207): batch is
        # non-empty here, so every observation is a cycle that moved
        # tensors; with metrics off the histogram is NULL and the span
        # degenerates to NULL_SPAN — no clock reads.
        with tele_span((), "CYCLE", histogram=self._m_cycle_s):
            try:
                if fusible:
                    self._run_fused_allreduces(backend, fusible,
                                               timeline)
                if sparse:
                    self._run_sparse_groups(backend, sparse, timeline)
                for e in others:
                    self._run_single(backend, e, timeline)
            finally:
                # Safety net for failure paths (idempotent: success
                # paths already released their names before completing
                # handles).
                with self._lock:
                    for e in batch:
                        if e.name:
                            self._pending_names.pop(
                                (e.process_set.process_set_id, e.name),
                                None)

    def _run_fused_allreduces(self, backend, entries, timeline):
        """Bucket by (process set, op, scales, dtype, codec), concat
        flattened tensors into fusion buffers bounded by the fusion
        threshold, and run one backend collective per buffer. The codec
        is part of the key so a compressed bucket is homogeneous — one
        quantized pipeline per buffer, never a mixed wire format."""
        import jax.numpy as jnp
        groups = {}
        for e in entries:
            a = e.arrays[0]
            pre = 1.0 if e.prescale is None else float(e.prescale)
            post = 1.0 if e.postscale is None else float(e.postscale)
            key = (e.process_set.process_set_id, e.op, pre, post,
                   str(jnp.asarray(a).dtype), e.codec)
            groups.setdefault(key, []).append(e)

        # Overlap mode trades the 128 MiB fusion ceiling for smaller
        # buckets: several independently dispatchable collectives per
        # cycle beat one giant barrier (docs/performance.md). Only the
        # single-controller (XlaSingle) and loopback backends reach this
        # path — backends that drive their own cycle (tcp/xla-global)
        # negotiate in _loop_native — so backend.allreduce here is the
        # lazy jax dispatch the async issue phase assumes.
        threshold = (self._bucket_bytes if self._overlap
                     else self.fusion_threshold)
        all_buckets = []
        for key, group in groups.items():
            # Split group into buckets under the threshold.
            cur, cur_bytes = [], 0
            for e in group:
                b = sum(_nbytes(jnp.asarray(a)) for a in e.arrays)
                if cur and cur_bytes + b > threshold:
                    all_buckets.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(e)
                cur_bytes += b
            if cur:
                all_buckets.append(cur)
        if not self._overlap or len(all_buckets) <= 1:
            for bucket in all_buckets:
                self._execute_allreduce_bucket(backend, bucket, timeline)
            return
        # Priority order: first-submitted first. Framework grad hooks
        # submit gradients in the order backprop produces them (last
        # layers first), so earlier entries are the ones the peer plane
        # has been ready to reduce longest.
        all_buckets.sort(key=lambda b: min(e.enqueue_time for e in b))
        issued = []
        for bucket in all_buckets:
            self._execute_allreduce_bucket(backend, bucket, timeline,
                                           issued=issued)
        if self._metrics_on and issued:
            self._observe_overlap(issued)

    def _execute_allreduce_bucket(self, backend, bucket, timeline,
                                  issued=None):
        """One fused collective for a bucket of allreduce entries.

        On TPU "fusion" means handing the whole bucket to one compiled XLA
        program — the backend receives the full list and XLA emits a single
        fused collective schedule, replacing the reference's hand-written
        batched memcpy kernels (reference: cuda/cuda_kernels.cu:45-139).

        Dispatch is asynchronous (jax arrays are futures): handles
        complete with lazy results and waiters force them off the cycle
        thread. On the overlap path (``issued`` is a list) the span is
        labeled per-bucket and (bucket, results, t_issued) is recorded
        so :meth:`_observe_overlap` can measure how much of each
        bucket's in-flight time stayed hidden under later dispatches.
        """
        e0 = bucket[0]
        names = [e.name for e in bucket]
        if self._metrics_on:
            self._record_fusion_stats(bucket)
        base = "fused_allreduce" if issued is None else "bucket_allreduce"
        span_kind = base if e0.codec is None else base + "_compressed"
        try:
            with tele_span(names, "FUSED_ALLREDUCE", timeline=timeline,
                           histogram=self._m_dispatch_s.labels(
                               kind=span_kind)):
                flat = []
                for e in bucket:
                    flat.extend(e.arrays)
                if e0.codec is not None:
                    results = self._run_compressed(backend, bucket,
                                                   flat, e0)
                else:
                    results = backend.allreduce(
                        flat, e0.op, e0.process_set,
                        prescale=e0.prescale, postscale=e0.postscale)
                if issued is not None:
                    issued.append((bucket, results, time.monotonic()))
                i = 0
                for e in bucket:
                    k = len(e.arrays)
                    # Release the name BEFORE completing the handle: a
                    # waiter may legally resubmit the same name the moment
                    # wait() returns (reference: tensor_queue erases the
                    # entry when the response is handed to the op layer).
                    self._release_name(e)
                    e.handle._complete(results[i:i + k] if k > 1
                                       else results[i])
                    if self._tracer is not None:
                        self._tracer.on_complete(e)
                    self.tensors_processed += k
                    self.bytes_processed += sum(_nbytes(a)
                                                for a in e.arrays)
                    i += k
        except Exception as exc:  # noqa: BLE001 - propagate to handles
            self._log.error("fused allreduce failed: %s", exc)
            for e in bucket:
                e.handle._fail(_wrap_error(exc))
                if self._tracer is not None:
                    self._tracer.on_complete(e, ok=False)

    def _run_compressed(self, backend, bucket, flat, e0):
        """One compressed fusion bucket (docs/compression.md). Cast
        codecs (fp16/bf16) ride a plain allreduce in the narrow dtype;
        wire codecs (int8/fp8) run the backend's quantized
        reduce-scatter → wide-dtype reduce → requantize → allgather
        pipeline, threading the error-feedback residuals through the
        plane's store. Backends without the pipeline (loopback) fall
        back to the plain allreduce — lossless, logged once."""
        from .compression import codecs as comp_codecs
        codec_name, block = e0.codec
        codec = comp_codecs.CODECS[codec_name]
        plane = self._compression
        if not codec.wire:
            import jax.numpy as jnp
            cast = [codec.encode(jnp.asarray(a), block)[0] for a in flat]
            results = backend.allreduce(
                cast, e0.op, e0.process_set,
                prescale=e0.prescale, postscale=e0.postscale)
            results = [r.astype(a.dtype)
                       for r, a in zip(results, flat)]
            plane.record(codec_name, bucket, flat, None)
            return results
        if not hasattr(backend, "allreduce_quantized"):
            plane.warn_fallback(backend.name)
            return backend.allreduce(
                flat, e0.op, e0.process_set,
                prescale=e0.prescale, postscale=e0.postscale)
        residuals = plane.residuals_in(bucket)
        results, new_residuals = backend.allreduce_quantized(
            flat, e0.op, e0.process_set, codec, block,
            prescale=e0.prescale, postscale=e0.postscale,
            residuals=residuals)
        if new_residuals is not None:
            plane.store_residuals(bucket, new_residuals)
        plane.record(codec_name, bucket, flat, new_residuals)
        return results

    # -- sparse gather path (ops/sparse.py; docs/sparse.md) ---------------
    def _run_sparse_groups(self, backend, entries, timeline):
        """Gather-path sparse allreduces: entries fuse by (process set,
        values dtype, index dtype, codec) and each group moves ONE
        uneven-allgather transport of concatenated (indices, values
        [, scales]) buffers — reusing the allgather_uneven plane — then
        scatter-adds per entry. Failures are isolated per group."""
        groups = {}
        for e in entries:
            m = e.sparse
            key = (e.process_set.process_set_id, m.values_dtype,
                   m.index_dtype, m.codec)
            groups.setdefault(key, []).append(e)
        for group in groups.values():
            self._execute_sparse_group(backend, group, timeline)

    def _execute_sparse_group(self, backend, group, timeline):
        import jax.numpy as jnp
        from .ops import sparse as sparse_mod
        e0 = group[0]
        names = [e.name for e in group]
        codec = e0.sparse.codec
        span_kind = ("sparse_allgather" if codec is None
                     else "sparse_allgather_compressed")
        try:
            with tele_span(names, "SPARSE_ALLGATHER", timeline=timeline,
                           histogram=self._m_dispatch_s.labels(
                               kind=span_kind)):
                if e0.sparse.nranks is None:
                    # Loopback (world-size-1 SPMD): this process holds
                    # the only slices — scatter-add locally, no wire.
                    for e in group:
                        dense = sparse_mod.scatter_add_dense(
                            e.arrays[0], e.arrays[1],
                            e.sparse.dense_shape, 1, e.op)
                        self._complete_sparse(e, dense)
                    return
                n = e0.sparse.nranks
                replicate = getattr(backend, "replicate_stacked", None)
                for e, dense in zip(group, self._sparse_gather_single(
                        backend, group, n, codec)):
                    if replicate is not None:
                        # Shard-by-shard: one (1, ...) block per mesh
                        # device, never the n-fold broadcast_to copy.
                        stacked = replicate(dense, e.process_set)
                    else:
                        stacked = jnp.broadcast_to(
                            dense[None], (n,) + e.sparse.dense_shape)
                    self._complete_sparse(e, stacked)
        except Exception as exc:  # noqa: BLE001 - propagate to handles
            self._log.error("sparse allgather failed: %s", exc)
            for e in group:
                e.handle._fail(_wrap_error(exc))
                if self._tracer is not None:
                    self._tracer.on_complete(e, ok=False)

    def _sparse_gather_single(self, backend, group, n, codec):
        """Single-controller transport for one sparse fusion group:
        per-rank concatenated (indices, flattened values[, row scales])
        buffers through ``backend.allgather_uneven`` (the ragged-shape
        plane the list-input allgather rides), boundaries kept locally.
        Yields each entry's dense reduction."""
        from .ops import sparse as sparse_mod
        row_elems = [sparse_mod.row_elems(e.sparse.dense_shape)
                     for e in group]
        counts = [[int(np.asarray(e.arrays[r]).shape[0]) for e in group]
                  for r in range(n)]
        idx_parts, val_parts, scale_parts = [], [], []
        idx_dtype = np.dtype(group[0].sparse.index_dtype)
        val_dtype = np.dtype(group[0].sparse.values_dtype)
        wire_dtype = np.int8 if codec == "int8" else val_dtype
        for r in range(n):
            idx_parts.append(np.concatenate(
                [np.asarray(e.arrays[r]).reshape(-1) for e in group]
            ).astype(idx_dtype, copy=False))
            vals, scales = [], []
            for e in group:
                v = np.asarray(e.arrays[e.sparse.nranks + r])
                if codec == "int8":
                    q, s = sparse_mod.encode_rows(v)
                    vals.append(np.asarray(q).reshape(-1))
                    scales.append(np.asarray(s).reshape(-1))
                else:
                    vals.append(v.reshape(-1))
            val_parts.append(
                np.concatenate(vals).astype(wire_dtype, copy=False)
                if vals else np.zeros(0, wire_dtype))
            if codec == "int8":
                scale_parts.append(
                    np.concatenate(scales).astype(np.float32,
                                                  copy=False))
        per_rank_lists = [idx_parts, val_parts]
        if codec == "int8":
            per_rank_lists.append(scale_parts)
        gathered = backend.allgather_uneven(per_rank_lists,
                                            group[0].process_set)
        # Every stacked slice is identical — slice 0 is the full
        # rank-major concatenation.
        full_idx = np.asarray(gathered[0])[0]
        full_val = np.asarray(gathered[1])[0]
        full_scale = (np.asarray(gathered[2])[0] if codec == "int8"
                      else None)
        # Per-rank cumulative entry offsets, computed ONCE: segment
        # extraction below is O(E*n) lookups, not O(E^2*n) re-summing
        # on the dispatch cycle thread.
        idx_cum, val_cum, idx_base, val_base = [], [], [], []
        idx_off = val_off = 0
        for r in range(n):
            ci = np.concatenate(([0], np.cumsum(counts[r])))
            cv = np.concatenate(([0], np.cumsum(
                [c * w for c, w in zip(counts[r], row_elems)])))
            idx_cum.append(ci)
            val_cum.append(cv)
            idx_base.append(idx_off)
            val_base.append(val_off)
            idx_off += int(ci[-1])
            val_off += int(cv[-1])
        results = []
        for ei, e in enumerate(group):
            tail = e.sparse.dense_shape[1:]
            idx_segs, val_segs, scale_segs = [], [], []
            for r in range(n):
                lo_i = idx_base[r] + int(idx_cum[r][ei])
                hi_i = idx_base[r] + int(idx_cum[r][ei + 1])
                lo_v = val_base[r] + int(val_cum[r][ei])
                hi_v = val_base[r] + int(val_cum[r][ei + 1])
                idx_segs.append(full_idx[lo_i:hi_i])
                val_segs.append(full_val[lo_v:hi_v])
                if codec == "int8":
                    scale_segs.append(full_scale[lo_i:hi_i])
            idx = np.concatenate(idx_segs)
            raw = np.concatenate(val_segs).reshape((-1,) + tuple(tail))
            if codec == "int8":
                vals = sparse_mod.decode_rows(
                    raw, np.concatenate(scale_segs), val_dtype)
            else:
                vals = raw
            results.append(sparse_mod.scatter_add_dense(
                idx, vals, e.sparse.dense_shape,
                len(e.process_set.ranks), e.op, dtype=val_dtype))
        return results

    def _complete_sparse(self, e, result):
        self._release_name(e)
        e.handle._complete(result)
        if self._tracer is not None:
            self._tracer.on_complete(e)
        self.tensors_processed += 1
        self.bytes_processed += sum(
            _nbytes(np.asarray(a)) for a in e.arrays)
        self._record_sparse_wire(e)

    def _record_sparse_wire(self, e):
        """Bytes-saved accounting vs the densified baseline (model
        bytes — docs/sparse.md methodology); no-op without a plane."""
        plane = self._sparse
        if plane is None:
            return
        from .ops import sparse as sparse_mod
        m = e.sparse
        world = len(e.process_set.ranks)
        if world <= 1:
            # Loopback / world-1: no fabric, nothing is "saved" — the
            # densified baseline would not have paid wire either.
            return
        k = m.nranks or 1
        nnz_total = sum(int(np.asarray(a).shape[0])
                        for a in e.arrays[:k])
        val_isize = np.dtype(m.values_dtype).itemsize
        idx_isize = np.dtype(m.index_dtype).itemsize
        plane.record_gather(
            sparse_mod.dense_wire_bytes(m.dense_shape, val_isize),
            sparse_mod.gather_wire_bytes(nnz_total,
                                         sparse_mod.row_elems(
                                             m.dense_shape),
                                         val_isize, idx_isize, world,
                                         codec=m.codec))

    def _observe_overlap(self, issued):
        """Metrics-on only: walk the overlap buckets in issue order and
        classify each bucket's in-flight time as *hidden* or *blocked*.
        A bucket found already complete (``is_ready``) before its force
        genuinely finished while the cycle thread was doing other work
        — issuing later buckets or draining earlier ones — and its
        whole flight counts as hidden; a bucket that still has to be
        forced counts only the force's wait, as blocked (time merely
        elapsed while we waited on an EARLIER bucket is NOT hidden —
        this collective may have made no progress then, so crediting it
        would inflate the gauge into meaninglessness on serial
        backends). ``hvd_overlap_fraction`` = hidden/(hidden+blocked);
        per-bucket hidden time feeds ``hvd_overlap_hidden_seconds``.
        Runs only under HOROVOD_TPU_METRICS: forcing results on the
        cycle thread is a measurement cost the default path must not
        pay (waiters force lazily in their own threads either way)."""
        import jax
        hidden = blocked = 0.0
        ready_at = {}

        def sweep(start, now):
            for j in range(start, len(issued)):
                if j not in ready_at and _results_ready(issued[j][1]):
                    ready_at[j] = now

        sweep(0, time.monotonic())
        for idx, (bucket, results, t_issued) in enumerate(issued):
            names = [e.name for e in bucket]
            if idx in ready_at:
                h = max(0.0, ready_at[idx] - t_issued)
                hidden += h
                self._m_overlap_hidden_s.observe(h)
                continue
            t0 = time.monotonic()
            try:
                with tele_span(names, "BUCKET_INFLIGHT",
                               timeline=self.runtime.timeline,
                               histogram=self._m_dispatch_s.labels(
                                   kind="bucket_wait")):
                    jax.block_until_ready(results)
            except Exception:  # noqa: BLE001 — surfaced to the waiter
                # A deferred collective failure raises at the waiter's
                # own force too; measurement must not eat the cycle.
                continue
            blocked += max(0.0, time.monotonic() - t0)
            # Later buckets that completed while this one blocked were
            # genuinely running concurrently — record before moving on.
            sweep(idx + 1, time.monotonic())
        if hidden + blocked > 0.0:
            self._m_overlap_fraction.set(hidden / (hidden + blocked))

    def _record_fusion_stats(self, bucket):
        """Fusion-plane accounting (metrics on only): queue-wait per
        entry, payload bytes by dtype, and fusion efficiency =
        payload / (payload + atomic-unit padding) — on TPU the fused
        element count rounds up to FUSION_ATOMIC_UNIT for XLA tiling, so
        the padding share is what a too-small bucket wastes."""
        now = time.monotonic()
        payload_elems = 0
        payload_bytes = 0
        for e in bucket:
            self._m_queue_wait_s.observe(now - e.enqueue_time)
            for a in e.arrays:
                payload_elems += int(np.prod(a.shape))
                payload_bytes += _nbytes(a)
        self._m_ops.labels(kind="allreduce").inc(len(bucket))
        itemsize = bucket[0].arrays[0].dtype.itemsize
        padded_elems = (-(-payload_elems // FUSION_ATOMIC_UNIT)
                        * FUSION_ATOMIC_UNIT)
        padding_bytes = (padded_elems - payload_elems) * itemsize
        self._m_fused_bytes.labels(
            dtype=str(bucket[0].arrays[0].dtype)).inc(payload_bytes)
        self._m_fusion_payload.inc(payload_bytes)
        self._m_fusion_padding.inc(padding_bytes)
        total = payload_bytes + padding_bytes
        if total:
            self._m_fusion_eff.set(payload_bytes / total)

    def _run_single(self, backend, e, timeline):
        if self._metrics_on:
            self._m_queue_wait_s.observe(time.monotonic()
                                         - e.enqueue_time)
            self._m_ops.labels(kind=e.kind).inc()
        try:
            with tele_span([e.name], e.kind.upper(), timeline=timeline,
                           histogram=self._m_dispatch_s.labels(
                               kind=e.kind)):
                out = self._dispatch_single(backend, e)
                self._release_name(e)
                e.handle._complete(out)
                if self._tracer is not None:
                    self._tracer.on_complete(e)
        except Exception as exc:  # noqa: BLE001
            self._log.error("%s failed for %s: %s", e.kind, e.name, exc)
            e.handle._fail(_wrap_error(exc))
            if self._tracer is not None:
                self._tracer.on_complete(e, ok=False)

    def _dispatch_single(self, backend, e):
        if e.kind == "allgather":
            if e.uneven:
                out = backend.allgather_uneven([e.arrays],
                                               e.process_set)[0]
            else:
                out = backend.allgather(e.arrays, e.process_set)
                out = out[0] if len(e.arrays) == 1 else out
        elif e.kind == "broadcast":
            out = backend.broadcast(e.arrays, e.root_rank, e.process_set)
            out = out[0] if len(e.arrays) == 1 else out
        elif e.kind == "alltoall":
            out = backend.alltoall(e.arrays[0], e.splits, e.process_set)
        elif e.kind == "reducescatter":
            out = backend.reducescatter(e.arrays, e.op, e.process_set)
            out = out[0] if len(e.arrays) == 1 else out
        elif e.kind == "barrier":
            backend.barrier(e.process_set)
            out = None
        else:
            raise ValueError(f"Unknown op kind {e.kind}")
        self.tensors_processed += len(e.arrays)
        self.bytes_processed += sum(
            _nbytes(np.asarray(a)) if not hasattr(a, "dtype") else
            _nbytes(a) for a in e.arrays)
        return out


def _results_ready(results):
    """True when every jax array in a bucket's results has completed
    (``is_ready``); non-jax results count as ready."""
    try:
        leaves = results if isinstance(results, (list, tuple)) \
            else [results]
        return all(r.is_ready() for r in leaves
                   if hasattr(r, "is_ready"))
    except Exception:  # noqa: BLE001 — a failed result is "done" too
        return True


def _wrap_error(exc):
    if isinstance(exc, (HorovodInternalError, DuplicateNameError, ValueError)):
        return exc
    return HorovodInternalError(str(exc))
