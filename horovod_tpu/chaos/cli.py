"""``hvd-chaos``: validate and inspect chaos fault-injection specs.

    hvd-chaos validate "kv_get:fail:n=3;worker:preempt:rank=1"
    hvd-chaos validate              # validates $HVDTPU_CHAOS
    hvd-chaos points                # list injection points + actions

Exit codes: 0 valid (or nothing to validate with a warning), 2 invalid
spec or usage error — the same convention as hvd-lint. Meant for CI:
validate the spec a chaos job will run with BEFORE burning cluster time
on it (a malformed spec otherwise fails at the first injection point
inside the job).
"""

import argparse
import sys

from ..utils import envparse
from .spec import ACTIONS, POINTS, ChaosSpecError, parse_spec


def _build_parser():
    parser = argparse.ArgumentParser(
        prog="hvd-chaos",
        description="Validate and inspect HVDTPU_CHAOS fault-injection "
                    "specs (docs/fault_tolerance.md).")
    sub = parser.add_subparsers(dest="command")
    val = sub.add_parser("validate",
                         help="parse a spec and print the rule table")
    val.add_argument("spec", nargs="?", default=None,
                     help="spec text (default: $HVDTPU_CHAOS)")
    sub.add_parser("points",
                   help="list injection points and actions")
    return parser


def _cmd_validate(spec_text):
    if spec_text is None:
        spec_text = envparse.get_str(envparse.CHAOS, "")
    if not spec_text:
        print("hvd-chaos: no spec given and HVDTPU_CHAOS is unset; "
              "nothing to validate")
        return 0
    try:
        rules = parse_spec(spec_text)
    except ChaosSpecError as exc:
        print(f"hvd-chaos: invalid spec: {exc}", file=sys.stderr)
        return 2
    print(f"hvd-chaos: {len(rules)} rule(s)")
    for i, rule in enumerate(rules):
        print(f"  [{i}] {rule.describe()}")
    return 0


def _cmd_points():
    print("Injection points:")
    for point, where in sorted(POINTS.items()):
        print(f"  {point:15s} {where}")
    print("Actions:")
    for action, what in sorted(ACTIONS.items()):
        print(f"  {action:15s} {what}")
    return 0


def main(argv=None):
    parser = _build_parser()
    args = parser.parse_args(argv)
    if args.command == "points":
        return _cmd_points()
    # Default command is validate (so `hvd-chaos` alone checks the env).
    return _cmd_validate(getattr(args, "spec", None))


if __name__ == "__main__":
    sys.exit(main())
