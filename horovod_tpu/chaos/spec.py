"""Chaos spec grammar: ``HVDTPU_CHAOS`` → a list of injection rules.

A spec is a ``;``-separated list of rules, each
``point:action[:param]*``:

    kv_get:fail:n=3
    kv_put:delay:ms=500
    worker:hang:rank=1
    worker:preempt:rank=2:after_commits=3
    collective:fail:name=grad_*:once

Params are ``key=value`` pairs (plus the bare ``once`` flag, shorthand
for ``n=1``). A param segment without ``=`` that is not a known flag is
re-joined to the previous value with the ``:`` restored, so worker ids
keep their natural spelling: ``worker:hang:wid=localhost:1``.

Matchers (``rank``, ``wid``, ``name``, ``kind``, ``scope``, ``key``,
``after_commits``) select WHEN a rule applies; budget params (``n``,
``after``, ``p``+``seed``, ``marker``) bound HOW OFTEN it fires; effect
params (``ms``, ``code``, ``err``) shape WHAT it does. Parsing is
strict — an unknown point, action, or param raises ``ChaosSpecError``
naming the offending rule, because a silently ignored chaos rule would
make a "passing" chaos test meaningless.
"""


class ChaosSpecError(ValueError):
    """HVDTPU_CHAOS could not be parsed; the message names the rule."""


# point -> where it is threaded (the `hvd-chaos points` catalog).
POINTS = {
    "kv_get": "runner/http_client.py — every GET attempt (per retry)",
    "kv_put": "runner/http_client.py — every PUT attempt (per retry)",
    "kv_delete": "runner/http_client.py — every DELETE attempt (per retry)",
    "kv_wait": "runner/http_client.py — each wait_for_kv poll iteration",
    "collective": "coordinator.py submit() — framework-thread collective "
                  "submissions (matchers: name, kind)",
    "backend_submit": "backend/tcp_backend.py submit_entry() — "
                      "native-plane submissions (matchers: name, kind)",
    "worker": "elastic.py State.commit() — commit boundaries "
              "(matchers: rank, wid, after_commits)",
    "heartbeat": "runner/heartbeat.py — each worker heartbeat beat",
    "checkpoint": "checkpoint.py save() — after the checkpoint file "
                  "lands (matchers: name = final file basename)",
    "driver": "runner/elastic_driver.py main loop + runner/standby.py "
              "poll loop — driver-process faults (actions: kill = "
              "SIGKILL the driver, partition = black-hole its KV/"
              "journal routes for ms=N; matcher wid=primary|standby "
              "selects the role; docs/fault_tolerance.md Control-plane "
              "HA)",
    "transfer": "fleet/arbiter.py — each lease-transfer state "
                "transition, fired after the ledger write and before "
                "the actuation it authorises (matchers: name = target "
                "state, kind = direction train_to_serve|"
                "serve_to_train; docs/fault_tolerance.md Fleet "
                "arbitration)",
    "drain": "fleet/actuators.py — raising the serving drain flag "
             "during a serve->train ebb (matcher: name = cohort)",
    "migrate_out": "serving/migration.py migrate_out() — each chunk "
                   "POST attempt of a KV-cache live migration, per "
                   "retry (matchers: key = request id, name = "
                   "migration id; fail raises a retryable transport "
                   "error, corrupt flips payload bytes AFTER the "
                   "digest was computed so the target must refuse)",
    "migrate_in": "serving/worker.py handle_migrate_in() — each "
                  "received migrate chunk (matchers: key = migration "
                  "id, name = cohort.wid; fail answers a retryable "
                  "503, corrupt flips received payload bytes before "
                  "digest verification)",
}

# action -> what firing does.
ACTIONS = {
    "fail": "raise a point-appropriate error (kv/heartbeat: retryable "
            "transport error, shaped by err=reset|refused|timeout; "
            "collective/backend_submit: HorovodInternalError; otherwise "
            "ChaosInjectedError)",
    "delay": "sleep ms=N milliseconds (default 100) before proceeding",
    "hang": "SIGSTOP the whole process — a truly hung worker (all "
            "threads, heartbeats included)",
    "preempt": "SIGTERM self — a simulated cloud preemption notice",
    "exit": "os._exit(code=N, default 17) — an abrupt crash",
    "mismatch": "corrupt the consistency digest this rank publishes for "
                "the matched collective (guardian.py detects and names "
                "this rank); needs HVDTPU_CONSISTENCY_CHECK",
    "stall": "swallow the matched submission — this rank never submits "
             "the op, peers stall on it (stuck-collective watchdog "
             "territory)",
    "corrupt": "flip bytes inside the just-written checkpoint payload "
               "so its checksum fails on restore; at the migrate "
               "points, flip KV page payload bytes so the sha256 "
               "digest check refuses the transfer",
    "kill": "SIGKILL the whole process — an abrupt driver-host death "
            "(no cleanup, no journal flush beyond what already "
            "fsync'd; the warm-standby takeover scenario)",
    "partition": "driver only: the KV store stops answering (requests "
                 "dropped without a response) for ms=N (default "
                 "5000) — a symmetric control-plane network partition",
}

# Signal actions are consumed by the injection site itself (the site
# catches chaos.ChaosSignal and applies the effect in its own terms),
# so they are only legal at points whose call sites understand them —
# anywhere else the signal would escape as a crash.
SIGNAL_ACTION_POINTS = {
    "mismatch": ("collective",),
    "stall": ("collective", "backend_submit"),
    "corrupt": ("checkpoint", "migrate_out", "migrate_in"),
    "partition": ("driver",),
}

_FLAGS = {"once"}
_INT_KEYS = {"n", "after", "after_commits", "ms", "code", "seed", "rank"}
_FLOAT_KEYS = {"p"}
_STR_KEYS = {"name", "kind", "scope", "key", "wid", "marker", "err"}
_ALL_KEYS = _INT_KEYS | _FLOAT_KEYS | _STR_KEYS
_ERR_KINDS = ("reset", "refused", "timeout")


class Rule:
    """One parsed injection rule. Attribute per known param; unset
    params are None (``after`` defaults to 0: fire from the first
    match)."""

    __slots__ = ("point", "action", "source", "n", "after",
                 "after_commits", "ms", "code", "seed", "rank", "p",
                 "name", "kind", "scope", "key", "wid", "marker", "err")

    def __init__(self, point, action, params, source):
        self.point = point
        self.action = action
        self.source = source
        for k in _ALL_KEYS:
            setattr(self, k, params.get(k))
        if self.after is None:
            self.after = 0

    def __repr__(self):
        return f"Rule({self.source!r})"

    def describe(self):
        parts = [f"{self.point}:{self.action}"]
        for k in sorted(_ALL_KEYS):
            v = getattr(self, k)
            if v is not None and not (k == "after" and v == 0):
                parts.append(f"{k}={v}")
        return "  ".join(parts)


def _join_value_segments(segments):
    """Re-join ``:``-split value fragments: a segment without ``=`` that
    is not a flag belongs to the previous param's value (worker ids are
    ``host:slot``)."""
    out = []
    for seg in segments:
        if "=" in seg or seg in _FLAGS or not out:
            out.append(seg)
        else:
            out[-1] += ":" + seg
    return out


def _parse_rule(text):
    parts = text.split(":")
    if len(parts) < 2:
        raise ChaosSpecError(
            f"chaos rule {text!r}: expected point:action[:param]*")
    point, action = parts[0].strip(), parts[1].strip()
    if point not in POINTS:
        raise ChaosSpecError(
            f"chaos rule {text!r}: unknown injection point {point!r} "
            f"(known: {', '.join(sorted(POINTS))})")
    if action not in ACTIONS:
        raise ChaosSpecError(
            f"chaos rule {text!r}: unknown action {action!r} "
            f"(known: {', '.join(sorted(ACTIONS))})")
    params = {}
    once = False
    for seg in _join_value_segments([p.strip() for p in parts[2:]]):
        if seg in _FLAGS:
            once = True
            continue
        key, _, value = seg.partition("=")
        if key not in _ALL_KEYS:
            raise ChaosSpecError(
                f"chaos rule {text!r}: unknown param {key!r} "
                f"(known: {', '.join(sorted(_ALL_KEYS | _FLAGS))})")
        if key in _INT_KEYS:
            try:
                params[key] = int(value)
            except ValueError:
                raise ChaosSpecError(
                    f"chaos rule {text!r}: param {key}={value!r} is not "
                    f"an integer")
        elif key in _FLOAT_KEYS:
            try:
                params[key] = float(value)
            except ValueError:
                raise ChaosSpecError(
                    f"chaos rule {text!r}: param {key}={value!r} is not "
                    f"a number")
        else:
            params[key] = value
    if once:
        if "n" in params:
            # One of them would silently win — exactly the "rule not
            # doing what the spec says" hazard strict parsing exists
            # to prevent.
            raise ChaosSpecError(
                f"chaos rule {text!r}: 'once' and 'n=' are mutually "
                f"exclusive")
        params["n"] = 1
    if "p" in params and not 0.0 < params["p"] <= 1.0:
        raise ChaosSpecError(
            f"chaos rule {text!r}: p must be in (0, 1]")
    if params.get("err") is not None and params["err"] not in _ERR_KINDS:
        raise ChaosSpecError(
            f"chaos rule {text!r}: err must be one of "
            f"{', '.join(_ERR_KINDS)}")
    allowed = SIGNAL_ACTION_POINTS.get(action)
    if allowed is not None and point not in allowed:
        raise ChaosSpecError(
            f"chaos rule {text!r}: action {action!r} is only valid at "
            f"point(s) {', '.join(allowed)} (its effect is applied by "
            f"those call sites)")
    return Rule(point, action, params, text)


def parse_spec(text):
    """Parse a full ``HVDTPU_CHAOS`` value into [Rule]."""
    rules = []
    for chunk in (text or "").split(";"):
        chunk = chunk.strip()
        if chunk:
            rules.append(_parse_rule(chunk))
    return rules
