"""horovod_tpu.chaos: deterministic fault injection for the control plane.

The elastic runtime's recovery paths (KV retry, heartbeat liveness,
graceful preemption — docs/fault_tolerance.md) are only trustworthy if
they can be exercised on demand. This subsystem threads named injection
points through the KV client, coordinator, native backend, elastic
commit loop, and heartbeat thread; ``HVDTPU_CHAOS`` selects what fires
where (grammar in spec.py, ``hvd-chaos`` CLI to validate it).

Cost model (the same contract as telemetry's disabled mode):

- **Disabled** (``HVDTPU_CHAOS`` unset): the spec resolves once, lazily,
  to the shared ``NULL_PLAN`` whose ``fire`` is empty — an injection
  point pays one global read + identity compare, allocates nothing, and
  mutates nothing. Hot paths additionally cache ``enabled()`` so the
  call itself is skipped.
- **Enabled**: rules are matched per point; every firing decision is
  driven by per-rule counters (``n``/``after``), an optional seeded RNG
  (``p``/``seed`` — crc32 of the rule text when no seed is given, so
  every process of a cohort samples identically), and an optional
  cross-process ``marker`` file (fire once per JOB, surviving elastic
  respawns). Fired injections log a warning, append to
  ``HVDTPU_CHAOS_LOG`` when set, and count
  ``hvd_chaos_injections_total{point,action}``.

A malformed spec raises ``ChaosSpecError`` at the first injection point
instead of silently disabling chaos — a chaos test that never injects
would pass for the wrong reason.
"""

import os
import random
import signal
import time
import urllib.error
import zlib

from ..exceptions import ChaosInjectedError, HorovodInternalError
from ..telemetry import core as telemetry
from ..utils import envparse
from ..utils.logging_util import get_logger
from .spec import (  # noqa: F401  (re-exported API)
    ACTIONS, POINTS, SIGNAL_ACTION_POINTS, ChaosSpecError, Rule,
    parse_spec,
)


class ChaosSignal(Exception):
    """A fired *signal* action (``mismatch``/``stall``/``corrupt``):
    the effect is applied by the injection site itself, so ``inject``
    raises this for the site to catch — never an error to surface.
    The spec parser rejects signal actions at points whose sites do
    not catch it (spec.SIGNAL_ACTION_POINTS)."""

    def __init__(self, action, rule):
        super().__init__(f"chaos signal {action} ({rule.source})")
        self.action = action
        self.rule = rule


class _NullPlan:
    """Shared no-op plan when chaos is off. One instance, no state."""

    __slots__ = ()
    rules = ()

    def fire(self, point, ctx):
        pass


NULL_PLAN = _NullPlan()


def _stable_seed(text):
    """Deterministic cross-process seed (``hash()`` is salted per
    interpreter; every rank must sample the same coin flips)."""
    return zlib.crc32(text.encode())


class _RuleState:
    """A Rule plus its per-process firing state."""

    __slots__ = ("rule", "hits", "fired", "_rng")

    def __init__(self, rule):
        self.rule = rule
        self.hits = 0
        self.fired = 0
        self._rng = random.Random(
            rule.seed if rule.seed is not None
            else _stable_seed(rule.source))

    def matches(self, ctx):
        import fnmatch
        r = self.rule
        if r.rank is not None:
            rank = ctx.get("rank")
            if rank is None:
                rank = envparse.get_int(envparse.RANK, -1)
            if int(rank) != r.rank:
                return False
        if r.wid is not None:
            wid = ctx.get("wid") or envparse.get_str(envparse.WORKER_ID)
            if wid != r.wid:
                return False
        if r.after_commits is not None:
            if int(ctx.get("commits", -1)) <= r.after_commits:
                return False
        for key in ("name", "kind", "scope", "key"):
            pat = getattr(r, key)
            if pat is None:
                continue
            value = ctx.get(key)
            if value is None or not fnmatch.fnmatchcase(str(value), pat):
                return False
        return True

    def take(self):
        """Consume one firing opportunity; True when the rule fires."""
        r = self.rule
        self.hits += 1
        if self.hits <= r.after:
            return False
        if r.n is not None and self.fired >= r.n:
            return False
        if r.p is not None and self._rng.random() >= r.p:
            return False
        if r.marker:
            # Atomic create = the cross-process fire-once lease: the
            # first process to fire wins; everyone else (including a
            # respawn of the same slot) sees the marker and skips.
            try:
                open(r.marker, "x").close()
            except FileExistsError:
                return False
            except OSError:
                pass  # unwritable marker dir: still fire, just unfenced
        self.fired += 1
        return True


class Plan:
    """Parsed rules grouped by point, plus firing bookkeeping."""

    def __init__(self, rules, log_path=""):
        self.rules = list(rules)
        self._log_path = log_path
        self._by_point = {}
        for rule in self.rules:
            self._by_point.setdefault(rule.point, []).append(
                _RuleState(rule))
        self._log = get_logger()
        self._m_injections = telemetry.counter(
            "hvd_chaos_injections_total",
            "Chaos rules fired", labelnames=("point", "action"))

    def fire(self, point, ctx):
        for rs in self._by_point.get(point, ()):
            if not rs.matches(ctx):
                continue
            if not rs.take():
                continue
            self._record(rs, point, ctx)
            _execute(rs.rule, point)

    def _record(self, rs, point, ctx):
        rule = rs.rule
        self._log.warning("chaos: firing %r at %s (ctx=%s, fired=%d)",
                          rule.source, point, ctx, rs.fired)
        self._m_injections.labels(point=point, action=rule.action).inc()
        # Flight-recorder breadcrumb: a postmortem that follows an
        # injection shows the injection next to the abort it caused.
        from .. import tracing
        tracing.trace_event("chaos", rule.action, point=point,
                            rule=rule.source)
        if self._log_path:
            try:
                with open(self._log_path, "a") as f:
                    f.write(f"{os.getpid()} {point} {rule.action} "
                            f"{rule.source} fired={rs.fired}\n")
            except OSError:
                pass


def _failure_for(rule, point):
    # migrate_out failures are transport-shaped so the migration
    # client's chunk-retry machinery (not a crash) absorbs them.
    if point.startswith("kv_") or point in ("heartbeat", "migrate_out"):
        err = rule.err or "reset"
        if err == "refused":
            return urllib.error.URLError(ConnectionRefusedError(
                f"chaos: injected connection refused ({rule.source})"))
        if err == "timeout":
            return TimeoutError(
                f"chaos: injected timeout ({rule.source})")
        return urllib.error.URLError(ConnectionResetError(
            f"chaos: injected connection reset ({rule.source})"))
    if point in ("collective", "backend_submit"):
        return HorovodInternalError(
            f"chaos: injected collective failure ({rule.source})")
    return ChaosInjectedError(
        f"chaos: injected failure ({rule.source})")


def _execute(rule, point):
    action = rule.action
    if action == "delay":
        # Injected on purpose — exempt from the hvd-sanitize blocking
        # tripwire so a chaos run with HVDTPU_SANITIZE=1 stays quiet.
        from ..analysis import sanitizer
        with sanitizer.allowed("chaos delay injection"):
            time.sleep((rule.ms if rule.ms is not None else 100)
                       / 1000.0)
    elif action == "fail":
        raise _failure_for(rule, point)
    elif action == "hang":
        os.kill(os.getpid(), signal.SIGSTOP)
    elif action == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif action == "preempt":
        os.kill(os.getpid(), signal.SIGTERM)
    elif action == "exit":
        os._exit(rule.code if rule.code is not None else 17)
    elif action in SIGNAL_ACTION_POINTS:
        raise ChaosSignal(action, rule)


_PLAN = None  # tri-state: None = not yet resolved


def _resolve():
    global _PLAN
    text = envparse.get_str(envparse.CHAOS, "")
    if not text:
        _PLAN = NULL_PLAN
    else:
        _PLAN = Plan(parse_spec(text),
                     log_path=envparse.get_str(envparse.CHAOS_LOG, ""))
    return _PLAN


def plan():
    """The resolved Plan (NULL_PLAN when chaos is off)."""
    return _PLAN if _PLAN is not None else _resolve()


def enabled():
    """True when HVDTPU_CHAOS carries at least one rule. Resolved once;
    hot paths cache this to skip the inject() call entirely."""
    return plan() is not NULL_PLAN


def reset():
    """Drop firing state and re-resolve from the environment (test
    hook; mirrors telemetry.reset)."""
    global _PLAN
    _PLAN = None


def inject(point, **ctx):
    """Fire any matching chaos rules at ``point``. The disabled path is
    one global read + identity compare."""
    p = _PLAN if _PLAN is not None else _resolve()
    if p is NULL_PLAN:
        return
    p.fire(point, ctx)
