"""Data-loading utilities (reference: horovod/data/__init__.py)."""

from .data_loader_base import (AsyncDataLoaderMixin,  # noqa: F401
                               BaseDataLoader, prefetch_to_device)
