"""Async data loading: background-thread prefetch mixin + device prefetch.

The reference ships ``AsyncDataLoaderMixin`` — a background thread that
pre-loads batches into a bounded queue while the training step runs
(reference: horovod/data/data_loader_base.py:165). On TPU the second half
of the story is ``prefetch_to_device``: moving the next batch into HBM
while the current step computes, so input transfer never serializes with
the MXU (the standard flax/jax prefetch idiom).
"""

import queue
import threading


class BaseDataLoader:
    """Iterable loader interface (reference: data_loader_base.py:25)."""

    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        """Yield batches for one epoch."""
        raise NotImplementedError


class AsyncDataLoaderMixin:
    """Mix in BEFORE a loader class to overlap loading with training
    (reference: data_loader_base.py:165 — same contract: a daemon thread
    fills a bounded queue; ``close()`` tears it down).

        class AsyncParquetLoader(AsyncDataLoaderMixin, ParquetLoader):
            pass
    """

    def __init__(self, async_loader_queue_size=8, *args, **kwargs):
        self.async_loader_queue_size = async_loader_queue_size
        self._async_queue = None
        self._async_thread = None
        self._async_stop = threading.Event()
        super().__init__(*args, **kwargs)

    def close_async_loader(self):
        """Stop the background thread (reference: close_async_loader)."""
        self._async_stop.set()
        if self._async_queue is not None:
            # Unblock a put()-blocked producer.
            try:
                while True:
                    self._async_queue.get_nowait()
            except queue.Empty:
                pass
        if self._async_thread is not None:
            self._async_thread.join(timeout=10)
            self._async_thread = None

    close = close_async_loader

    def _async_worker(self, q):
        try:
            for batch in super().__iter__():
                while not self._async_stop.is_set():
                    try:
                        q.put((batch, None), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._async_stop.is_set():
                    return
            q.put((None, None))  # epoch sentinel
        except Exception as e:  # noqa: BLE001 — re-raised on the consumer
            q.put((None, e))

    def __iter__(self):
        if self.async_loader_queue_size <= 0:
            yield from super().__iter__()
            return
        if self._async_thread is not None and self._async_thread.is_alive():
            # Previous epoch abandoned mid-iteration (consumer broke out):
            # tear its producer down before starting a new one, or the old
            # thread leaks blocked on the abandoned queue.
            self.close_async_loader()
        self._async_stop.clear()
        q = queue.Queue(maxsize=self.async_loader_queue_size)
        self._async_queue = q
        self._async_thread = threading.Thread(
            target=self._async_worker, args=(q,), daemon=True,
            name="hvdtpu-async-loader")
        self._async_thread.start()
        while True:
            batch, exc = q.get()
            if exc is not None:
                raise exc
            if batch is None:
                break
            yield batch
        self._async_thread.join(timeout=10)
        self._async_thread = None


def prefetch_to_device(iterator, size=2, devices=None):
    """Wrap a host batch iterator so the next ``size`` batches are already
    on (or on their way to) the device while the current step runs — the
    TPU half of async loading (input HBM transfer overlaps compute).

    Each batch (a pytree of arrays) is jax.device_put eagerly into a small
    deque; with a single device the transfer is async by construction.
    """
    import collections

    import jax

    target = devices[0] if devices else None

    def put(batch):
        if target is None:
            return jax.tree.map(jax.device_put, batch)
        return jax.tree.map(lambda x: jax.device_put(x, target), batch)

    buf = collections.deque()
    it = iter(iterator)

    def gen():
        try:
            while len(buf) < size:
                buf.append(put(next(it)))
        except StopIteration:
            pass
        while buf:
            out = buf.popleft()
            try:
                buf.append(put(next(it)))
            except StopIteration:
                pass
            yield out

    return gen()
