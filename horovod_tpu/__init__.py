"""horovod_tpu: a TPU-native distributed training framework.

Provides the capabilities of the reference data-parallel framework
(Horovod; see SURVEY.md) re-designed for TPU: the eager data plane is
jitted XLA collectives over the ICI mesh, the compiled path is pjit/
shard_map sharding (see horovod_tpu.parallel), and the job machinery
(launcher, elastic, autotune, timeline) is re-built around TPU-VM slices.

Public API shape follows the reference's per-framework modules
(reference: horovod/torch/mpi_ops.py, horovod/common/basics.py).
"""

from .version import __version__  # noqa: F401

from .basics import (  # noqa: F401
    init, shutdown, is_initialized, rank, size, local_rank, local_size,
    cross_rank, cross_size, mesh, is_homogeneous, metrics_snapshot,
    mpi_enabled, mpi_built, gloo_enabled, gloo_built, nccl_built,
    ddl_built, ccl_built, cuda_built, rocm_built, xla_built,
    mpi_threads_supported,
)
from .exceptions import (  # noqa: F401
    HorovodInternalError, HostsUpdatedInterrupt, NotInitializedError,
    DuplicateNameError, StalledTensorError, SubmissionOrderError,
    CollectiveLintError,
)
from .ops.reduce_ops import (  # noqa: F401
    Average, Sum, Adasum, Min, Max, Product,
)
from .ops.compression import Compression  # noqa: F401
from .ops.collectives import (  # noqa: F401
    allreduce, allreduce_, allreduce_async, allreduce_async_,
    grouped_allreduce, grouped_allreduce_, grouped_allreduce_async,
    grouped_allreduce_async_,
    allgather, allgather_async, grouped_allgather, grouped_allgather_async,
    broadcast, broadcast_, broadcast_async, broadcast_async_,
    alltoall, alltoall_async,
    reducescatter, reducescatter_async, grouped_reducescatter,
    grouped_reducescatter_async,
    barrier, join, poll, synchronize,
)
from .ops.sparse import (  # noqa: F401
    SparseGradient, sparse_allreduce, sparse_allreduce_async,
)
from .process_sets import (  # noqa: F401
    ProcessSet, global_process_set, add_process_set, remove_process_set,
)
from .functions import (  # noqa: F401
    broadcast_object, broadcast_parameters, broadcast_optimizer_state,
    broadcast_variables, allgather_object,
)
from . import elastic  # noqa: F401  (hvd.elastic.run / State / ObjectState)


def __getattr__(name):
    # horovod_tpu.run(func, num_proc=N) — the reference's programmatic
    # launcher (horovod/runner/__init__.py:92 ``horovod.run``). Lazy so
    # importing the package never pulls the runner machinery.
    if name == "run":
        from .runner import run
        return run
    if name in ("analysis", "telemetry"):
        # hvd.analysis.check_fn / hvd.telemetry.counter etc. — lazy so
        # importing the package never loads the subsystem.
        # (importlib, not `from . import`: the latter resolves through
        # this very __getattr__ and recurses.)
        import importlib
        return importlib.import_module("." + name, __name__)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def start_timeline(file_path, mark_cycles=None, jax_profiler_dir=None):
    """Start recording a Chrome-trace timeline at runtime (reference:
    horovod/common/basics.py:156 start_timeline). ``jax_profiler_dir``
    additionally captures a jax.profiler device trace alongside the host
    timeline (the TPU analog of the reference's NVTX ranges).
    ``mark_cycles`` defaults to the HVDTPU_TIMELINE_MARK_CYCLES env knob
    (hvdrun --timeline-mark-cycles) so the launcher flag applies to
    runtime-started timelines too."""
    from . import basics
    from .timeline import Timeline
    from .utils import envparse
    rt = basics.runtime()
    if rt.timeline is not None:
        rt.timeline.stop()
    if mark_cycles is None:
        mark_cycles = envparse.get_bool(envparse.TIMELINE_MARK_CYCLES)
    rt.timeline = Timeline(file_path, jax_profiler_dir=jax_profiler_dir,
                           mark_cycles=mark_cycles)
    rt.timeline.start()


def stop_timeline():
    """Stop the runtime timeline (reference: horovod/common/basics.py
    stop_timeline)."""
    from . import basics
    rt = basics.runtime()
    if rt.timeline is not None:
        rt.timeline.stop()
        rt.timeline = None
